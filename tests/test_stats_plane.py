"""Runtime statistics plane: plan-shape fingerprints, the on-disk plan
history store, history-primed footprint estimates and the stats read-outs.

Covers: fingerprint stability (literal values normalized out, dtypes and
group keys kept), history round-trip across two sessions through the same
directory (run 2 hits, estimate error shrinks, results bit-identical),
corrupt/empty history files degrading to the static estimate with a warning
— never a query failure, the per-node observed-stats ledger (rows,
selectivity, dispatch mirrors, host<->device transfer bytes), the
plan.stats event-log record, explain(stats=True), the footprint knobs
(scheduler.footprint.{floorBytes,decodeExpansion}) and the profiler's
``stats`` subcommand."""

import json
import os
import subprocess
import sys

import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu.runtime import eventlog as EL
from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.runtime import history as H
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.session import TpuSession

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    EL.shutdown()
    faults.reset()
    M.reset_global_registry()
    tracing.clear_events()
    H.shutdown()
    yield
    EL.shutdown()
    faults.reset()
    M.reset_global_registry()
    tracing.clear_events()
    H.shutdown()


def _session(**extra):
    return TpuSession(dict(extra))


def _table(n=300):
    return pa.table({"k": [1, 2, 3] * (n // 3),
                     "v": [1.0, 2.0, 3.0] * (n // 3),
                     "w": list(range(n))})


def _fingerprint_of(spark, df):
    df.collect()
    return spark.last_query_metrics().footprint["fingerprint"]


# -- plan-shape fingerprint ---------------------------------------------------

def test_fingerprint_ignores_literal_values():
    spark = _session()
    df = spark.create_dataframe(_table())
    a = _fingerprint_of(spark, df.filter(F.col("v") > F.lit(1.0)))
    b = _fingerprint_of(spark, df.filter(F.col("v") > F.lit(250.0)))
    assert a == b, "literal VALUE must not change the plan shape"


def test_fingerprint_keeps_dtypes_and_keys():
    spark = _session()
    df = spark.create_dataframe(_table())
    base = _fingerprint_of(spark, df.group_by("k").agg(
        F.sum(F.col("v")).alias("s")))
    other_key = _fingerprint_of(spark, df.group_by("w").agg(
        F.sum(F.col("v")).alias("s")))
    assert base != other_key, "group key is part of the shape"
    # int literal vs float literal: the literal's DTYPE stays significant
    a = _fingerprint_of(spark, df.filter(F.col("w") > F.lit(10)))
    b = _fingerprint_of(spark, df.filter(F.col("v") > F.lit(10.0)))
    assert a != b


def test_fingerprint_is_deterministic_across_sessions():
    a = _session()
    b = _session()
    fa = _fingerprint_of(a, a.create_dataframe(_table()).group_by("k").agg(
        F.sum(F.col("v")).alias("s")))
    fb = _fingerprint_of(b, b.create_dataframe(_table()).group_by("k").agg(
        F.sum(F.col("v")).alias("s")))
    assert fa == fb


# -- history store ------------------------------------------------------------

def test_history_round_trip_across_sessions(tmp_path):
    hist = str(tmp_path / "hist")

    def run():
        spark = _session(**{
            "spark.rapids.tpu.stats.history.dir": hist,
            "spark.rapids.tpu.scheduler.footprint.floorBytes": "1k"})
        df = spark.create_dataframe(_table()).group_by("k").agg(
            F.sum(F.col("v")).alias("s"))
        out = df.collect()
        qm = spark.last_query_metrics()
        return out, qm.footprint, qm.stats

    out1, fp1, st1 = run()
    assert fp1["history_hit"] is False
    assert os.path.exists(os.path.join(hist, "plan_history.json"))
    out2, fp2, st2 = run()
    assert fp2["history_hit"] is True
    assert fp2["fingerprint"] == fp1["fingerprint"]
    # the recorded observation IS the estimate: error collapses on run 2
    assert st2["estimate_error"] <= st1["estimate_error"]
    assert fp2["estimate"] >= st1["peak_device_bytes"]
    assert out1.to_pydict() == out2.to_pydict()


def test_corrupt_history_degrades_to_static(tmp_path, caplog):
    hist = tmp_path / "hist"
    hist.mkdir()
    (hist / "plan_history.json").write_text("{not json!!")
    spark = _session(**{"spark.rapids.tpu.stats.history.dir": str(hist)})
    df = spark.create_dataframe(_table()).group_by("k").agg(
        F.sum(F.col("v")).alias("s"))
    out = df.collect()          # must not raise
    assert out.num_rows == 3
    fp = spark.last_query_metrics().footprint
    assert fp["history_hit"] is False
    assert fp["estimate"] == fp["static"]
    assert any("history" in r.message.lower() for r in caplog.records)


def test_history_disabled_by_knob(tmp_path):
    hist = str(tmp_path / "hist")
    conf = {"spark.rapids.tpu.stats.history.dir": hist,
            "spark.rapids.tpu.stats.history.enabled": "false"}
    for _ in range(2):
        spark = _session(**conf)
        df = spark.create_dataframe(_table()).group_by("k").agg(
            F.sum(F.col("v")).alias("s"))
        df.collect()
        fp = spark.last_query_metrics().footprint
        assert fp["history_hit"] is False
    assert not os.path.exists(os.path.join(hist, "plan_history.json"))


def test_history_evicts_to_max_shapes(tmp_path):
    store = H.PlanHistoryStore(str(tmp_path), max_shapes=2)
    for i in range(5):
        store.record(f"fp{i:02d}", {"peak_device_bytes": 100 + i})
    assert store.shape_count() == 2
    # newest entries survive LRU eviction
    reloaded = H.PlanHistoryStore(str(tmp_path), max_shapes=2)
    assert reloaded.lookup("fp04") is not None
    assert reloaded.lookup("fp00") is None


def test_history_record_merges_peak(tmp_path):
    class _Mem(H.PlanHistoryStore):
        def _store(self, shapes):
            self._shapes = shapes

    s = _Mem.__new__(_Mem)
    s.max_shapes = 8
    s._dir = None
    # record() takes the cross-process advisory lock at <path>.lock even
    # when _store is overridden, so the mock needs a real lockable path
    s.path = str(tmp_path / "plan_history.json")
    import threading
    s._lock = threading.Lock()
    s._shapes = {}
    s._load = lambda: dict(s._shapes)
    s.record("fp", {"peak_device_bytes": 100, "out_rows": 5})
    e = s.record("fp", {"peak_device_bytes": 40, "out_rows": 7})
    assert e["runs"] == 2
    assert e["peak_device_bytes"] == 100   # max across runs, never shrinks
    assert e["out_rows"] == 7              # cardinalities track the latest


# -- per-node ledger, plan.stats record and read-outs -------------------------

def test_node_ledger_and_plan_stats_event(tmp_path):
    spark = _session(**{"spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    df = spark.create_dataframe(_table(), num_partitions=2)
    q = df.group_by("k").agg(F.sum(F.col("v")).alias("s")).sort("k")
    res = q.collect()
    assert res.num_rows == 3
    qm = spark.last_query_metrics()
    st = qm.stats
    assert st is not None and st["fingerprint"]
    nodes = {n["name"]: n for n in st["nodes"]}
    agg = next(v for k, v in nodes.items() if "Aggregate" in k)
    assert agg["rows"] >= 3 and agg["output_bytes"] > 0
    # selectivity: aggregate reduces 300 input rows to 3 groups
    final_aggs = [v for k, v in nodes.items()
                  if "Aggregate" in k and v.get("selectivity")]
    assert any(v["selectivity"] <= 0.5 for v in final_aggs)
    # dispatch mirror: at least one node ran a compiled kernel
    assert any(n.get("dispatches") for n in st["nodes"])
    # host->device ledger: the ArrowScan uploaded real bytes
    assert any(n.get("h2d_bytes") for n in st["nodes"])
    # the exchange's per-reduce-partition sizes ride in
    assert st["shuffles"] and st["shuffles"][0]["partitions"] == 2
    assert st["shuffles"][0]["max_partition"] in (0, 1)

    path = EL.current_path()
    EL.shutdown()
    recs = [json.loads(line) for line in open(path)]
    ps = [r for r in recs if r["event"] == "plan.stats"]
    assert len(ps) == 1
    assert EL.validate_record(ps[0]) == []
    assert ps[0]["query"] == qm.query_id
    assert ps[0]["fingerprint"] == st["fingerprint"]
    end = [r for r in recs if r["event"] == "query.end"][0]
    assert "estimate_error" in end and "history_hit" in end


def test_explain_stats_annotation():
    spark = _session()
    df = spark.create_dataframe(_table())
    q = df.group_by("k").agg(F.sum(F.col("v")).alias("s"))
    q.collect()
    s = q.explain(stats=True)
    assert "footprint:" in s and "fingerprint=" in s
    assert "rows=3" in s and "h2d=" in s
    # before any action the annotated form explains itself
    fresh = spark.create_dataframe(_table())
    assert "no completed action" in fresh.explain(stats=True)


def test_footprint_floor_knob():
    from spark_rapids_tpu.runtime import scheduler as SCHED
    spark = _session(**{
        "spark.rapids.tpu.scheduler.footprint.floorBytes": "128m"})
    df = spark.create_dataframe(_table())
    est = SCHED.estimate_footprint(df._plan, spark.conf)
    assert est >= 128 << 20
    small = _session(**{
        "spark.rapids.tpu.scheduler.footprint.floorBytes": "1k"})
    assert SCHED.estimate_footprint(df._plan, small.conf) < 128 << 20


def test_footprint_decode_expansion_knob(tmp_path):
    import numpy as np
    from spark_rapids_tpu.runtime import scheduler as SCHED
    t = pa.table({"a": np.arange(50000, dtype=np.int64)})
    import pyarrow.parquet as pq
    pq.write_table(t, str(tmp_path / "f.parquet"))
    lo = _session(**{
        "spark.rapids.tpu.scheduler.footprint.floorBytes": "1k",
        "spark.rapids.tpu.scheduler.footprint.decodeExpansion": "1.0"})
    hi = _session(**{
        "spark.rapids.tpu.scheduler.footprint.floorBytes": "1k",
        "spark.rapids.tpu.scheduler.footprint.decodeExpansion": "10.0"})
    plan = lo.read_parquet(str(tmp_path / "f.parquet"))._plan
    e_lo = SCHED.estimate_footprint(plan, lo.conf)
    e_hi = SCHED.estimate_footprint(plan, hi.conf)
    assert e_hi > e_lo * 5


def _run_profiler(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "profiler.py"), *args],
        capture_output=True, text=True, cwd=REPO)


def test_profiler_stats_subcommand(tmp_path):
    spark = _session(**{"spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    df = spark.create_dataframe(_table(), num_partitions=2)
    df.group_by("k").agg(F.sum(F.col("v")).alias("s")).collect()
    path = EL.current_path()
    EL.shutdown()

    proc = _run_profiler("stats", path)
    assert proc.returncode == 0, proc.stderr
    assert "footprint estimate error" in proc.stdout
    assert "node ledger" in proc.stdout
    assert "at partition" in proc.stdout      # skew row names the partition

    proc = _run_profiler("stats", path, "--json")
    assert proc.returncode == 0, proc.stderr
    d = json.loads(proc.stdout)
    assert d["violations"] == []
    qs = [q for q in d["queries"] if q["stats"]]
    assert qs and qs[0]["stats"]["peak_device_bytes"] >= 0
    assert qs[0]["shuffles"]


def test_cluster_map_stage_feeds_skew(tmp_path):
    """When the cluster plane runs the map stage (executors write the
    blocks, the driver only sees MapOutputTracker split sizes), the
    per-reduce-partition totals must still reach the ambient collector AND
    the driver's event log, so the profiler skew table is not blind on
    cluster runs."""
    import numpy as np
    from spark_rapids_tpu.cluster import MiniCluster

    spark = _session(**{"spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    rng = np.random.default_rng(7)
    df = spark.create_dataframe(
        pa.table({"k": rng.integers(0, 50, 4000), "v": rng.random(4000)}),
        num_partitions=2)
    q = df.group_by("k").agg(F.sum(F.col("v")).alias("s"))
    col = M.QueryMetricsCollector("cluster group-by")
    with MiniCluster(n_executors=2, platform="cpu") as c:
        with M.collector_context(col):
            out = c.collect(q)
    assert out.num_rows == 50
    shuffles = col.shuffle_stats()
    assert shuffles, "cluster map stage recorded no partition sizes"
    assert sum(shuffles[0]["partition_sizes"]) > 0
    path = EL.current_path()
    EL.shutdown()
    recs = [json.loads(line) for line in open(path)]
    ends = [r for r in recs if r["event"] == "stage.map.end"
            and r.get("partition_sizes")]
    assert ends, "driver log has no stage.map.end with partition sizes"
    assert EL.validate_record(ends[-1]) == []


def test_profiler_stats_errors_without_records(tmp_path):
    log = tmp_path / "events-empty.jsonl"
    log.write_text("")
    proc = _run_profiler("stats", str(log))
    assert proc.returncode == 1
    assert "no plan.stats" in proc.stderr
