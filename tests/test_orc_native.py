"""ORC stage-one device decode (io/orc_native.py + ops/orc_decode.py) vs
the pyarrow host reader (reference GpuOrcScan role, SURVEY.md #24)."""

import numpy as np
import pyarrow as pa
import pyarrow.orc as orc
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.io import orc_native as ON
from spark_rapids_tpu.session import TpuSession


def mixed_table(n=20000, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "a": pa.array(np.arange(n, dtype=np.int64)),              # delta
        "b": pa.array(rng.integers(-1 << 40, 1 << 40, n)),        # direct
        "c": pa.array([None if i % 7 == 0 else int(v) for i, v in
                       enumerate(rng.integers(0, 1000, n))],
                      pa.int64()),                                # nulls
        "d": pa.array(rng.normal(size=n)),                        # double
        "e": pa.array(np.full(n, 42, dtype=np.int64)),            # repeat
        "i32": pa.array(rng.integers(-100, 100, n).astype(np.int32)),
        "s": pa.array([f"g{i % 9}" for i in range(n)]),           # fallback
    })


@pytest.fixture(scope="module")
def orc_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("orcdev")
    t = mixed_table()
    p = str(d / "t.orc")
    orc.write_table(t, p, compression="uncompressed")
    return p, t


def test_meta_matches_pyarrow(orc_file):
    p, t = orc_file
    meta = ON.read_meta(p)
    pf = orc.ORCFile(p)
    assert len(meta.stripes) == pf.nstripes
    assert sum(s.num_rows for s in meta.stripes) == t.num_rows
    assert meta.column_names == t.column_names


def test_stripe_device_matches_host(orc_file):
    p, t = orc_file
    meta = ON.read_meta(p)
    schema = T.StructType([
        T.StructField("a", T.LONG), T.StructField("b", T.LONG),
        T.StructField("c", T.LONG), T.StructField("d", T.DOUBLE),
        T.StructField("e", T.LONG), T.StructField("i32", T.INT),
        T.StructField("s", T.STRING)])
    got = {f.name: [] for f in schema.fields}
    for si in range(len(meta.stripes)):
        at = ON.read_stripe_device(p, meta, si, schema).to_arrow()
        for name in got:
            got[name].extend(at[name].to_pylist())
    for name in got:
        exp = t[name].to_pylist()
        if name == "d":
            assert all(abs(g - e) < 1e-12 for g, e in zip(got[name], exp))
        else:
            assert got[name] == exp, name


def test_session_orc_scan_device_equals_host(orc_file):
    p, t = orc_file
    on = TpuSession({"spark.rapids.tpu.sql.orc.deviceDecode.enabled":
                      "true"}).read_orc(p).collect()
    off = TpuSession({"spark.rapids.tpu.sql.orc.deviceDecode.enabled":
                      "false"}).read_orc(p).collect()
    for name in t.column_names:
        a, b = on[name].to_pylist(), off[name].to_pylist()
        if name == "d":
            assert all(abs(x - y) < 1e-12 for x, y in zip(a, b))
        else:
            assert a == b, name


@pytest.mark.parametrize("codec", ["zlib", "snappy"])
def test_compressed_orc_device_path(tmp_path, codec):
    """Default-config writers compress (zlib is the ORC spec default); the
    stripe streams inflate on host and decode on device — no fallback."""
    t = mixed_table(2000, seed=3)
    p = str(tmp_path / f"{codec}.orc")
    orc.write_table(t, p, compression=codec)
    meta = ON.read_meta(p)
    assert meta.compression == (ON.C_ZLIB if codec == "zlib" else ON.C_SNAPPY)
    schema = T.StructType([
        T.StructField("a", T.LONG), T.StructField("b", T.LONG),
        T.StructField("c", T.LONG), T.StructField("d", T.DOUBLE),
        T.StructField("e", T.LONG), T.StructField("i32", T.INT),
        T.StructField("s", T.STRING)])
    got = {f.name: [] for f in schema.fields}
    for si in range(len(meta.stripes)):
        at = ON.read_stripe_device(p, meta, si, schema).to_arrow()
        for name in got:
            got[name].extend(at[name].to_pylist())
    for name in got:
        exp = t[name].to_pylist()
        if name == "d":
            assert all(abs(g - e) < 1e-12 for g, e in zip(got[name], exp))
        else:
            assert got[name] == exp, name


def test_direct_strings_device_path(tmp_path):
    """DIRECT_V2 strings (pyarrow's writer default: dictionary disabled)
    decode on the device path including nulls."""
    n = 4000
    t = pa.table({"s": pa.array(
        [None if i % 13 == 0 else f"value-{i}-{i % 7}" for i in range(n)])})
    p = str(tmp_path / "direct.orc")
    orc.write_table(t, p, compression="zlib")
    meta = ON.read_meta(p)
    schema = T.StructType([T.StructField("s", T.STRING)])
    got = []
    for si in range(len(meta.stripes)):
        got.extend(
            ON.read_stripe_device(p, meta, si, schema).to_arrow()["s"]
            .to_pylist())
    assert got == t["s"].to_pylist()


def test_boolean_rle_decode():
    # literal run: header = 256 - 2 → 2 literal bytes
    buf = bytes([254, 0b10100000, 0b11000000])
    bits = ON.decode_boolean_rle(buf, 12)
    assert list(bits) == [1, 0, 1, 0, 0, 0, 0, 0, 1, 1, 0, 0]
    # repeat run: header 0 → 3 copies of next byte
    buf2 = bytes([0, 0b11111111])
    assert list(ON.decode_boolean_rle(buf2, 24)) == [1] * 24


def test_rlev2_delta_and_shortrepeat():
    import io as _io
    # craft: short-repeat of 5 (count 4, width 1 byte, zigzag(5)=10)
    sr = bytes([0b00000001, 10])
    runs = ON.scan_rlev2(sr, 0, len(sr), 4, True)
    assert runs[0][0] == "const" and list(runs[0][2]) == [5, 5, 5, 5]


def test_string_dictionary_v2_device_path(tmp_path):
    """DICTIONARY_V2 strings decode through the engine dictionary path —
    asserted directly on string_column_to_device, not via fallback."""
    n = 3000
    t = pa.table({"s": pa.array([None if i % 11 == 0 else f"g{i % 25}"
                                 for i in range(n)])})
    p = str(tmp_path / "s.orc")
    # pyarrow's ORC writer disables dictionary encoding by default
    orc.write_table(t, p, compression="uncompressed",
                    dictionary_key_size_threshold=1.0)
    meta = ON.read_meta(p)
    si = meta.stripes[0]
    with open(p, "rb") as f:
        f.seek(si.offset)
        raw = f.read(si.index_length + si.data_length + si.footer_length)
    rel = ON.StripeInfo()
    rel.offset, rel.index_length = 0, si.index_length
    rel.data_length, rel.footer_length = si.data_length, si.footer_length
    streams, encodings = ON._read_stripe_footer(raw, rel)
    enc1, dict_size1 = encodings[1]
    assert enc1 == ON.E_DICTIONARY_V2 and dict_size1 == 25
    off, offsets = 0, {}
    for kind, col, length in streams:
        offsets[(kind, col)] = (off, length)
        off += length
    present = None
    if (ON.S_PRESENT, 1) in offsets:
        poff, plen = offsets[(ON.S_PRESENT, 1)]
        present = ON.decode_boolean_rle(raw[poff:poff + plen], si.num_rows)
    from spark_rapids_tpu.columnar.vector import bucket_capacity
    cv = ON.string_column_to_device(raw, offsets, 1, present, si.num_rows,
                                    bucket_capacity(si.num_rows),
                                    n_dict=dict_size1)
    assert cv.dictionary is not None and len(cv.dictionary) == 25
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu import types as T2
    batch = ColumnarBatch([cv], si.num_rows,
                          T2.StructType([T2.StructField("s", T2.STRING)]))
    assert batch.to_arrow()["s"].to_pylist() == \
        t["s"].to_pylist()[:si.num_rows]


def test_rlev2_patched_base_spec_golden():
    """The official ORC spec's PATCHED_BASE example: base 2000, 8-bit
    values, one 12-bit patch at gap 3 producing 1000000."""
    buf = bytes([0x8e, 0x09, 0x2b, 0x21, 0x07, 0xd0, 0x1e, 0x00, 0x14,
                 0x70, 0x28, 0x32, 0x3c, 0x46, 0x50, 0x5a, 0xfc, 0xe8])
    runs = ON.scan_rlev2(buf, 0, len(buf), 10, True)
    assert runs[0][0] == "const"
    assert [int(v) for v in runs[0][2]] == \
        [2030, 2000, 2020, 1000000, 2040, 2050, 2060, 2070, 2080, 2090]


def test_rlev2_patched_base_nonaligned_patch_width():
    """pgw+pw that is NOT an encodable width must read patch entries at
    getClosestFixedBits(pgw+pw) like real writers (here 3+22=25 → 26)."""
    import numpy as np

    def pack_msb(values, width):
        bits = []
        for v in values:
            bits.extend((v >> (width - 1 - i)) & 1 for i in range(width))
        while len(bits) % 8:
            bits.append(0)
        by = bytearray()
        for i in range(0, len(bits), 8):
            by.append(int("".join(map(str, bits[i:i + 8])), 2))
        return bytes(by)

    # 6 values width 4; base 100; one patch at gap 3: patch=0x2ABCDE (22 bits)
    w, cnt, bw, pw, pgw, pll = 4, 6, 1, 22, 3, 1
    vals = [1, 2, 3, 4, 5, 6]
    hdr0 = 0x80 | (3 << 1)          # enc=2, width code 3 → 4 bits, len hi 0
    hdr1 = cnt - 1
    hdr2 = ((bw - 1) << 5) | 21     # code 21 → 22 bits
    hdr3 = ((pgw - 1) << 5) | pll
    base = bytes([100])
    payload = pack_msb(vals, w)
    patch_val = 0x2ABCDE
    entry = (3 << pw) | patch_val    # gap 3, patch
    cw = ON._closest_fixed_bits(pgw + pw)
    assert cw == 26
    patches = pack_msb([entry], cw)
    buf = bytes([hdr0, hdr1, hdr2, hdr3]) + base + payload + patches
    runs = ON.scan_rlev2(buf, 0, len(buf), cnt, True)
    got = [int(v) for v in runs[0][2]]
    expect = [101, 102, 103, 100 + (4 | (patch_val << w)), 105, 106]
    assert got == expect
