"""Distributed tracing plane: cross-process trace propagation, span files,
Perfetto export + critical path, live serving metrics, compile telemetry.

Covers the telemetry contracts of runtime/tracing.py + tools/profiler.py
trace: a per-query trace id derived from the query id rides the MiniCluster
task protocol (surviving an exec_kill respawn), spans from every process
merge into one clock-offset-corrected Chrome trace, the endpoint serves a
Prometheus-style STATS snapshot backed by the fixed-bucket histograms in
runtime/metrics.py, and fuse compile/dispatch deltas reach
last_query_metrics() (the zero-retrace denominator)."""

import importlib.util
import json
import os
import pathlib
import sys

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu.cluster import MiniCluster
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.runtime import eventlog
from spark_rapids_tpu.runtime import faults as FLT
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.session import TpuSession

REPO = pathlib.Path(__file__).resolve().parent.parent


def _profiler():
    spec = importlib.util.spec_from_file_location(
        "profiler_mod", REPO / "tools" / "profiler.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_state():
    FLT.reset()
    tracing.clear_events()
    yield
    FLT.reset()
    tracing.clear_events()
    tracing.shutdown_spans()
    tracing.set_process_trace(None)
    eventlog.set_clock_offset(0.0)


# ---------------------------------------------------------------------------
# histogram bucket math
# ---------------------------------------------------------------------------

def test_histogram_bucket_math():
    h = M.Histogram("t", bounds=(0.1, 1.0, 10.0))
    for v in (0.05, 0.05, 0.5, 2.0, 50.0):
        h.observe(v)
    snap = h.snapshot()
    # bucket i counts v <= bounds[i]; the 4th bucket is the +inf overflow
    assert snap["counts"] == [2, 1, 1, 1]
    assert snap["count"] == 5
    assert abs(snap["sum"] - 52.6) < 1e-9
    assert snap["min"] == 0.05 and snap["max"] == 50.0
    # percentiles are monotone in q and clamped to observed [min, max]
    ps = [h.percentile(q) for q in (0.1, 0.5, 0.9, 0.99)]
    assert ps == sorted(ps)
    assert ps[0] >= 0.05 and ps[-1] <= 50.0
    assert h.percentile(1.0) == 50.0
    assert M.Histogram("empty").percentile(0.5) is None


def test_histogram_registry_and_percentile_helper():
    M.histogram("test.reg.lat").observe(0.2)
    M.histogram("test.reg.lat").observe(0.4)
    snap = M.histograms_snapshot()["test.reg.lat"]
    assert snap["count"] == 2
    pct = M.histogram_percentiles("test.reg.lat")
    assert pct["count"] == 2 and pct["p50"] <= pct["p95"] <= pct["p99"]
    assert M.histogram_percentiles("no.such.histogram") is None


# ---------------------------------------------------------------------------
# clock-offset correction
# ---------------------------------------------------------------------------

def test_clock_offset_estimator():
    # symmetric latency: exact recovery of the remote clock skew
    # local sends at 100.0, remote (running 7s ahead) answers at 107.05,
    # local receives at 100.1 -> offset ≈ -7 (remote + offset = local)
    off = tracing.estimate_clock_offset(100.0, 107.05, 100.1)
    assert abs(off - (-7.0)) < 1e-9
    # the error of any estimate is bounded by half the round trip
    off = tracing.estimate_clock_offset(100.0, 107.0, 100.5)
    assert abs(off - (-6.75)) < 1e-9


def test_clock_offset_correction_in_merge(tmp_path):
    """Two processes whose RAW timestamps order wrongly must order
    correctly once each record's `off` correction is applied."""
    prof = _profiler()
    # driver: query window [1000, 1001]
    (tmp_path / "spans-1-a.jsonl").write_text(json.dumps(
        {"name": "query", "ph": "X", "ts": 1000.0, "dur": 1.0, "pid": 1,
         "proc": "driver", "tid": "MainThread", "trace": "t1"}) + "\n")
    # executor clock runs 10s BEHIND: raw ts 990.5 is really 1000.5
    (tmp_path / "spans-2-b.jsonl").write_text(json.dumps(
        {"name": "task.map", "ph": "X", "ts": 990.5, "dur": 0.2, "off": 10.0,
         "pid": 2, "proc": "executor-0", "tid": "MainThread",
         "trace": "t1"}) + "\n")
    records, violations = prof.load_spans(str(tmp_path))
    assert violations == []
    tid, spans = prof.pick_trace(records, "t1")
    assert len(spans) == 2
    by_name = {s["name"]: s for s in spans}
    assert by_name["task.map"]["_t0"] == pytest.approx(1000.5)
    # inside the driver window — uncorrected it would precede it entirely
    assert by_name["query"]["_t0"] < by_name["task.map"]["_t0"]
    window, chain, blame = prof.critical_path(spans)
    assert window["wall_s"] == pytest.approx(1.0)
    names = [c["name"] for c in chain]
    assert "task.map" in names
    task = next(c for c in chain if c["name"] == "task.map")
    assert task["start_s"] == pytest.approx(0.5)
    assert blame.get("compute", 0) == pytest.approx(0.2)


def test_eventlog_records_carry_pid_and_offset(tmp_path):
    eventlog.set_clock_offset(3.25)
    path = eventlog.configure(str(tmp_path))
    try:
        eventlog.emit("endpoint.start", query=None, host="x", port=1)
    finally:
        eventlog.shutdown()
        eventlog.set_clock_offset(0.0)
    rec = json.loads(open(path).read().strip())
    assert rec["pid"] == os.getpid()
    assert rec["offset"] == 3.25
    assert isinstance(rec["ts"], float)
    assert eventlog.validate_record(rec) == []


# ---------------------------------------------------------------------------
# span files + trace context
# ---------------------------------------------------------------------------

def test_span_file_schema_and_trace_precedence(tmp_path):
    path = tracing.configure_spans(str(tmp_path), process="driver")
    reg = M.MetricsRegistry("DEBUG")
    timer = reg.metric("opTime")
    with tracing.trace_context("tls-trace"):
        with tracing.trace_range("ProjectExec", timer):
            pass
    tracing.set_process_trace("proc-trace")
    with tracing.span("task.map", split=3):
        pass
    tracing.span_event("oom.retry", site="joins.build")
    tracing.set_process_trace(None)
    with tracing.span("orphan"):
        pass
    tracing.shutdown_spans()
    recs = [json.loads(ln) for ln in open(path)]
    for r in recs:
        assert tracing.validate_span(r) == [], r
    by_name = {r["name"]: r for r in recs}
    # thread-local context beats everything; process default fills in for
    # executor-style threads; no ambient context -> None
    assert by_name["ProjectExec"]["trace"] == "tls-trace"
    assert by_name["task.map"]["trace"] == "proc-trace"
    assert by_name["oom.retry"]["trace"] == "proc-trace"
    assert by_name["oom.retry"]["ph"] == "i"
    assert by_name["orphan"]["trace"] is None
    # the metric side of trace_range still accumulated
    assert timer.value > 0
    assert by_name["ProjectExec"]["dur"] > 0


def test_chrome_trace_schema(tmp_path):
    prof = _profiler()
    path = tracing.configure_spans(str(tmp_path), process="driver")
    with tracing.trace_context("c1"), tracing.span("query"):
        with tracing.span("FilterExec"):
            pass
        tracing.span_event("spill", bytes=10)
    tracing.shutdown_spans()
    records, violations = prof.load_spans(str(tmp_path))
    assert violations == []
    tid, spans = prof.pick_trace(records)
    assert tid == "c1" and len(spans) == 3
    trace = prof.chrome_trace(spans)
    evs = trace["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    body = [e for e in evs if e["ph"] != "M"]
    assert {m["name"] for m in meta} == {"process_name", "thread_name"}
    for e in body:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(e)
        assert isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert "dur" in e
        else:
            assert e["ph"] == "i"
        assert e["args"]["trace"] == "c1"
    # instants for span events ride along
    assert any(e["ph"] == "i" and e["name"] == "spill" for e in body)


def test_malformed_span_file_is_a_violation(tmp_path):
    prof = _profiler()
    (tmp_path / "spans-9-z.jsonl").write_text('{"broken json\n')
    records, violations = prof.load_spans(str(tmp_path))
    assert records == [] and violations
    # missing-field records are violations too, not crashes
    (tmp_path / "spans-9-z.jsonl").write_text(
        json.dumps({"name": "x", "ph": "X", "ts": 1.0}) + "\n")
    records, violations = prof.load_spans(str(tmp_path))
    assert records == [] and any("dur" in v or "pid" in v
                                 for v in violations)


# ---------------------------------------------------------------------------
# MiniCluster propagation with one exec_kill recompute
# ---------------------------------------------------------------------------

def test_minicluster_trace_propagation_with_exec_kill(tmp_path):
    """The full distributed contract: one trace id across driver + 3
    executor processes, surviving an executor SIGKILL mid-map-stage (the
    respawned incarnation's spans carry the SAME trace id), merging into a
    schema-valid Chrome trace with a non-empty critical path."""
    prof = _profiler()
    rng = np.random.default_rng(11)
    t = pa.table({"k": pa.array(rng.integers(0, 13, 3000), type=pa.int64()),
                  "v": pa.array(rng.integers(0, 100, 3000),
                                type=pa.int64())})
    spark = TpuSession()
    df = (spark.create_dataframe(t, num_partitions=6)
          .group_by(F.col("k")).agg(F.sum(F.col("v")).alias("s")))
    exp = sorted(map(tuple, (r.values() for r
                             in df.collect_host().to_pylist())))

    settings = {
        "spark.rapids.tpu.trace.dir": str(tmp_path),
        # SIGKILL executor 0 after its first map task parked blocks
        "spark.rapids.tpu.test.faults": "exec_kill:cluster.map.0:1@1",
    }
    tracing.configure_spans(str(tmp_path), process="driver")
    base = M.resilience_snapshot()
    with MiniCluster(n_executors=3, conf=RapidsConf(settings),
                     platform="cpu") as c:
        got = c.collect(df)
    tracing.shutdown_spans()
    delta = {k: v - base[k] for k, v in M.resilience_snapshot().items()
             if v - base[k]}
    assert delta.get("executorsLost", 0) >= 1, delta
    assert delta.get("stagePartialRecomputes", 0) >= 1, delta
    assert sorted(map(tuple, (r.values() for r in got.to_pylist()))) == exp

    records, violations = prof.load_spans(str(tmp_path))
    assert violations == [], violations[:5]
    trace_id, spans = prof.pick_trace(records)
    assert trace_id.startswith("cluster-")
    # spans from the driver AND >= 3 executor incarnations (the original
    # three minus the killed one plus its respawn) share the trace id
    pids = {s["pid"] for s in spans}
    procs = {s["proc"] for s in spans}
    assert len(pids) >= 4, (pids, procs)
    assert "driver" in procs
    assert sum(1 for p in procs if p.startswith("executor-")) >= 3, procs
    # executor-0 appears under TWO pids: the killed incarnation wrote task
    # spans before dying, the respawn wrote the recompute's — same trace
    exec0_pids = {s["pid"] for s in spans if s["proc"] == "executor-0"}
    assert len(exec0_pids) >= 2, (exec0_pids, procs)
    # Chrome export + critical path (the ci.sh gate's in-suite twin)
    trace = prof.chrome_trace(spans)
    assert len(trace["traceEvents"]) > len(spans)   # + metadata lanes
    window, chain, blame = prof.critical_path(spans)
    assert window is not None and chain, (window, chain)
    assert window["name"] == "cluster.query"
    assert sum(blame.values()) <= window["wall_s"] + 1e-6
    assert max(blame, key=blame.get) in (
        "compute", "decode", "exchange", "queue-wait", "other")
    # task spans exist on both stages
    names = {s["name"] for s in spans}
    assert "task.map" in names and "task.result" in names


# ---------------------------------------------------------------------------
# STATS over the endpoint
# ---------------------------------------------------------------------------

def test_stats_roundtrip_over_endpoint():
    from spark_rapids_tpu.runtime.endpoint import EndpointClient
    spark = TpuSession()
    spark.create_or_replace_temp_view(
        "t", spark.create_dataframe(
            pa.table({"k": [1, 2, 2], "v": [1.0, 2.0, 3.0]})))
    ep = spark.serve()
    try:
        cli = EndpointClient(("127.0.0.1", ep.port))
        out = cli.submit("select k, sum(v) s from t group by k order by k",
                         trace="client-trace-7")
        assert out.num_rows == 2
        # the client's trace id rode the SUBMIT frame into the collector
        # (the summary frame reads it back off qm.trace_id server-side)
        assert cli.last_summary["trace"] == "client-trace-7"
        txt = cli.stats()
    finally:
        ep.shutdown(grace_s=2)
    assert "srt_queries_admitted_total" in txt
    assert 'srt_resilience_total{counter="numOomRetries"}' in txt
    assert "srt_scheduler_queue_depth" in txt
    assert 'srt_gauge{name="endpoint.connections"}' in txt
    # histogram families: latency per priority class + admission wait,
    # cumulative buckets ending in +Inf == count
    assert 'srt_query_latency_seconds_bucket{priority="0",le="+Inf"}' in txt
    assert "srt_admission_wait_seconds_count" in txt
    inf = [ln for ln in txt.splitlines()
           if ln.startswith('srt_query_latency_seconds_bucket{priority="0"')
           and 'le="+Inf"' in ln]
    cnt = [ln for ln in txt.splitlines()
           if ln.startswith('srt_query_latency_seconds_count')]
    assert inf and cnt and inf[0].split()[-1] == cnt[0].split()[-1]


def test_stats_disabled_returns_typed_error():
    from spark_rapids_tpu.runtime.endpoint import EndpointClient
    spark = TpuSession({"spark.rapids.tpu.endpoint.stats.enabled": "false"})
    ep = spark.serve()
    try:
        cli = EndpointClient(("127.0.0.1", ep.port))
        with pytest.raises(RuntimeError, match="stats.enabled"):
            cli.stats()
    finally:
        ep.shutdown(grace_s=2)


# ---------------------------------------------------------------------------
# compile/retrace telemetry
# ---------------------------------------------------------------------------

def test_compile_metrics_zero_retrace_on_second_run():
    spark = TpuSession()
    t = pa.table({"k": pa.array([1, 2, 2, 3] * 50, type=pa.int64()),
                  "v": pa.array(list(range(200)), type=pa.int64())})
    df = (spark.create_dataframe(t)
          .filter(F.col("v") >= 10)
          .group_by(F.col("k")).agg(F.sum(F.col("v")).alias("s")))
    df.collect()
    first = spark.last_query_metrics().compile_metrics()
    assert first["dispatches"] > 0
    df.collect()
    second = spark.last_query_metrics().compile_metrics()
    # the retrace denominator: an identical second run replays cached
    # kernels — zero new XLA compiles, same order of dispatches
    assert second["compiles"] == 0, (first, second)
    assert second["dispatches"] > 0
    # surfaced in the annotated plan header (explain(metrics=True))
    header = df.explain(metrics=True).splitlines()[0]
    assert "compiles=0" in header and "dispatches=" in header


def test_compile_metrics_in_query_end_event(tmp_path):
    spark = TpuSession()
    path = eventlog.configure(str(tmp_path))
    try:
        t = pa.table({"a": pa.array([1, 2, 3], type=pa.int64())})
        spark.create_dataframe(t).filter(F.col("a") > 1).collect()
    finally:
        eventlog.shutdown()
    ends = [json.loads(ln) for ln in open(path)
            if '"query.end"' in ln]
    assert ends, "no query.end recorded"
    rec = ends[-1]
    assert isinstance(rec["compiles"], int)
    assert isinstance(rec["dispatches"], int)
    assert rec["dispatches"] > 0
