"""Multi-process cluster ring — the reference's local-cluster mode analog
(SURVEY.md §4 ring 3: pseudo-distributed runs exist to surface
serialization and wire-format bugs that in-process tests can't).

Real worker PROCESSES each host a block store + TCP shuffle server; the
driver process fetches every reduce partition from every worker over real
sockets and checks contents against independently re-generated expected
tables. Spawn context (fresh interpreters), like the reference's executors.
Signaling is file-based: multiprocessing queues/events shared with
terminated children can deadlock the parent's interpreter exit.
"""

import multiprocessing as mp
import os
import time

import numpy as np
import pyarrow as pa
import pytest


def _expected_table(worker: int, rid: int) -> pa.Table:
    rng = np.random.default_rng(worker * 100 + rid)
    n = 50 + rid * 7
    return pa.table({
        "k": pa.array(rng.integers(0, 1000, n)),
        "v": pa.array(rng.normal(size=n)),
        "s": pa.array([f"w{worker}r{rid}x{i % 5}" for i in range(n)]),
    })


def _worker_main(worker: int, n_reduce: int, report_path: str):
    """One 'executor': fill a local block store, serve it over TCP, then
    idle until the driver terminates us."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import spark_rapids_tpu  # noqa: F401
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
    from spark_rapids_tpu.shuffle.transport import TcpTransport

    store = ShuffleBlockStore.get()
    sid = store.register_shuffle(serialized=True)
    for rid in range(n_reduce):
        store.write_block(sid, rid,
                          ColumnarBatch.from_arrow(_expected_table(worker,
                                                                   rid)))
    transport = TcpTransport(RapidsConf(
        {"spark.rapids.tpu.shuffle.compression.codec": "lz4"}))
    tmp = report_path + ".tmp"
    with open(tmp, "w") as f:
        f.write(f"{transport.port} {sid}")
    os.replace(tmp, report_path)
    time.sleep(300)  # parent terminates us


def _await_report(path: str, timeout: float = 60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            port, sid = open(path).read().split()
            return int(port), int(sid)
        time.sleep(0.1)
    raise TimeoutError(path)


def _spawn_worker(ctx, worker, n_reduce, tmp_path):
    report = str(tmp_path / f"worker-{worker}.addr")
    p = ctx.Process(target=_worker_main, args=(worker, n_reduce, report),
                    daemon=True)
    p.start()
    return p, report


def test_cluster_ring_cross_process_fetch(tmp_path):
    n_workers, n_reduce = 2, 3
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.shuffle.transport import TcpTransport

    ctx = mp.get_context("spawn")
    procs = [_spawn_worker(ctx, w, n_reduce, tmp_path)
             for w in range(n_workers)]
    try:
        peers = [(w, *_await_report(report))
                 for w, (_p, report) in enumerate(procs)]
        transport = TcpTransport(RapidsConf(
            {"spark.rapids.tpu.shuffle.compression.codec": "lz4"}))
        try:
            for worker, port, sid in peers:
                client = transport.make_client(("127.0.0.1", port))
                for rid in range(n_reduce):
                    batches = list(client.fetch_blocks(sid, rid))
                    assert batches, (worker, rid)
                    got = pa.concat_tables([b.to_arrow() for b in batches])
                    exp = _expected_table(worker, rid)
                    assert got.column("k").to_pylist() == \
                        exp.column("k").to_pylist()
                    assert got.column("s").to_pylist() == \
                        exp.column("s").to_pylist()
                    assert np.allclose(got.column("v").to_numpy(),
                                       exp.column("v").to_numpy())
        finally:
            transport.shutdown()
    finally:
        for p, _ in procs:
            p.terminate()
            p.join(timeout=30)


def test_cluster_ring_dead_peer_surfaces_transport_error(tmp_path):
    """Failure-detection ring: killing a worker process turns subsequent
    fetches into TransportError (the reference maps this to
    FetchFailedException → stage retry, RapidsShuffleIterator.scala:82);
    a raw ConnectionRefusedError would escape the recompute ladder."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.shuffle.transport import TcpTransport, TransportError

    ctx = mp.get_context("spawn")
    p, report = _spawn_worker(ctx, 0, 2, tmp_path)
    try:
        port, sid = _await_report(report)
        transport = TcpTransport(RapidsConf())
        try:
            client = transport.make_client(("127.0.0.1", port))
            assert list(client.fetch_blocks(sid, 0))   # alive: works
            p.terminate()
            p.join(timeout=30)
            with pytest.raises(TransportError):
                client2 = transport.make_client(("127.0.0.1", port))
                list(client2.fetch_blocks(sid, 1))
        finally:
            transport.shutdown()
    finally:
        p.terminate()
        p.join(timeout=30)
