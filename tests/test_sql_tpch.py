"""Official TPC-H SQL text (q1/q3/q5) through session.sql(), value-checked
against the independent NumPy oracles — the same equality the DataFrame
suite (test_tpch.py) enforces. Also covers the typed-literal grammar
(DATE '...', INTERVAL 'n' unit) the official text depends on."""

import pytest

from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.sql.tpch_queries import SQL_QUERIES

SF = 0.01


@pytest.fixture(scope="module")
def env():
    paths = tpch.generate(SF, f"/tmp/tpch_sf{SF}")
    spark = TpuSession()
    tpch.load(spark, paths, files_per_partition=2)  # registers temp views
    return spark, tpch.load_np(paths)


def test_sql_q1_matches_oracle(env):
    spark, tb = env
    got = spark.sql(SQL_QUERIES["q1"]).collect().to_pylist()
    exp = tpch.np_q1(tb)
    assert len(got) == len(exp)
    for g_, e in zip(got, exp):
        g = list(g_.values())
        assert g[0] == e[0] and g[1] == e[1]
        for a, b in zip(g[2:], e[2:]):
            assert abs(a - b) <= 1e-6 * max(1.0, abs(b)), (g, e)


def test_sql_q3_matches_oracle(env):
    spark, tb = env
    got = spark.sql(SQL_QUERIES["q3"]).collect().to_pylist()
    exp = tpch.np_q3(tb)
    assert len(got) == len(exp)
    for g, (k, d, p, rev) in zip(got, exp):
        assert g["l_orderkey"] == k
        assert abs(g["revenue"] - rev) <= 1e-6 * max(1.0, abs(rev))


def test_sql_q5_matches_oracle(env):
    spark, tb = env
    got = spark.sql(SQL_QUERIES["q5"]).collect().to_pylist()
    exp = tpch.np_q5(tb)
    assert len(got) == len(exp)
    for g, (n, v) in zip(got, exp):
        assert g["n_name"] == n
        assert abs(g["revenue"] - v) <= 1e-6 * max(1.0, abs(v))


def test_typed_literals_grammar():
    spark = TpuSession()
    import pyarrow as pa
    spark.create_or_replace_temp_view(
        "t", spark.create_dataframe(pa.table({"x": pa.array([1], pa.int64())})))
    row = spark.sql(
        "select date '2020-03-01' as d, "
        "date '2020-03-01' + interval '2' day as d2, "
        "date '2020-03-01' - interval '1' month as m, "
        "date '2020-01-31' + interval '1' month as clamp, "
        "date '2020-03-01' + interval '1' week as w, "
        "timestamp '2020-03-01 12:30:00' as ts from t").collect().to_pylist()[0]
    import datetime
    assert row["d"] == datetime.date(2020, 3, 1)
    assert row["d2"] == datetime.date(2020, 3, 3)
    assert row["m"] == datetime.date(2020, 2, 1)
    assert row["clamp"] == datetime.date(2020, 2, 29)   # month-end clamp
    assert row["w"] == datetime.date(2020, 3, 8)
    assert row["ts"].replace(tzinfo=None) == datetime.datetime(2020, 3, 1, 12, 30)
