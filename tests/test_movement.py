"""Data-movement observability plane (runtime/movement.py): the per-link
byte ledger, fetch-attempt retry reclassification, the per-query collector
mirror, and the cluster-level link-honesty + ledger-integrity invariants.

The two headline contracts this file pins down:

  * link honesty (the misattribution fix): a same-host MiniCluster moves
    plenty of TCP bytes but ZERO cross-host bytes — every transport byte
    classifies ``loopback`` and every in-process short-circuit ``local``,
    so the ``tcp`` row of the ledger can never be inflated by loopback
    traffic;
  * no-double-count under chaos: a killed executor plus a corrupted
    (CRC-failed, retried) fetch still leave total shuffle.recv payload
    equal to the map-output bytes the driver registered — failed attempts'
    bytes move to the ``shuffle.retry`` edge instead of piling onto recv.
"""

import glob
import json

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu.cluster import MiniCluster
from spark_rapids_tpu.cluster import remote as R
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.runtime import eventlog as EL
from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import movement as MV
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(autouse=True)
def _clean_state():
    EL.shutdown()
    faults.reset()
    M.reset_global_registry()
    M.reset_observability()          # clears the movement ledger too
    tracing.clear_events()
    yield
    EL.shutdown()
    faults.reset()
    M.reset_global_registry()
    M.reset_observability()
    tracing.clear_events()


def _last_samples(eventlog_dir):
    """Last (cumulative) movement.sample per process + driver-registered
    map-output bytes, from every per-process event file in the directory."""
    samples, registered = {}, 0
    for path in glob.glob(str(eventlog_dir) + "/events-*.jsonl"):
        with open(path, encoding="utf-8") as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                rec = json.loads(ln)
                if rec.get("event") == "movement.sample":
                    samples[rec.get("pid")] = rec
                elif rec.get("event") == "stage.map.end" \
                        and rec.get("partition_sizes"):
                    registered += sum(rec["partition_sizes"])
    return samples, registered


def _flow_sum(samples, field, pred):
    return sum(fl[field] for rec in samples.values()
               for fl in rec.get("flows") or [] if pred(fl))


# -- ledger core --------------------------------------------------------------

def test_record_and_snapshot():
    MV.record("shuffle.send", 1000, link="loopback", site="t")
    # wire record with a trimmed payload, then a payload-only follow-up
    MV.record("shuffle.send", 500, link="loopback", site="t",
              payload_bytes=450)
    MV.record("shuffle.recv", 0, link="loopback", site="t",
              payload_bytes=300, transfers=0)
    snap = MV.snapshot()
    c = snap[("shuffle.send", "loopback", "t")]
    assert (c["bytes"], c["payload_bytes"], c["transfers"]) == (1500, 1450, 2)
    r = snap[("shuffle.recv", "loopback", "t")]
    assert (r["bytes"], r["payload_bytes"], r["transfers"]) == (0, 300, 0)
    assert MV.edge_link_totals()[("shuffle.send", "loopback")]["bytes"] == 1500
    assert MV.total_bytes() == 1500
    MV.reset()
    assert MV.snapshot() == {} and MV.total_bytes() == 0


def test_configure_enabled_gates_recording():
    MV.configure(enabled=False)
    try:
        assert not MV.enabled()
        MV.record("h2d", 123, link="pcie", site="t")
        assert MV.total_bytes() == 0
    finally:
        MV.configure(enabled=True)
    MV.record("h2d", 123, link="pcie", site="t")
    assert MV.total_bytes() == 123


def test_classify_peer():
    assert MV.classify_peer(None) == "local"
    assert MV.classify_peer(("127.0.0.1", 7337)) == "loopback"
    assert MV.classify_peer(("localhost", 7337)) == "loopback"
    assert MV.classify_peer(("::1", 7337)) == "loopback"
    assert MV.classify_peer(("10.1.2.3", 7337)) == "tcp"
    # this process's own registered block-server host is same-host by
    # definition, whatever IP it registered under
    prev = R.local_address()
    R.set_local_address(("10.1.2.3", 9999))
    try:
        assert MV.classify_peer(("10.1.2.3", 7337)) == "loopback"
        assert MV.classify_peer(("10.9.9.9", 7337)) == "tcp"
    finally:
        R.set_local_address(prev)


def test_transfer_histograms_fed_by_timed_records():
    MV.record("shuffle.recv", 4096, link="loopback", site="t", seconds=0.01)
    h = M.histograms_snapshot()
    assert h["movement.transfer.bytes"]["count"] == 1
    assert h["movement.transfer.bytes"]["max"] == 4096.0
    assert h["movement.transfer.latency"]["count"] == 1


# -- fetch-attempt reclassification (the shuffle.retry edge) ------------------

def test_attempt_abort_moves_recv_to_retry():
    tok = MV.begin_attempt()
    MV.record("shuffle.recv", 800, link="loopback", site="transport.fetch",
              payload_bytes=700)
    MV.abort_attempt(tok)
    snap = MV.snapshot()
    recv = snap[("shuffle.recv", "loopback", "transport.fetch")]
    assert recv["bytes"] == 0 and recv["payload_bytes"] == 0
    retry = snap[("shuffle.retry", "loopback", "transport.fetch")]
    assert retry["bytes"] == 800 and retry["payload_bytes"] == 700
    # a committed attempt's bytes stay on recv
    tok2 = MV.begin_attempt()
    MV.record("shuffle.recv", 300, link="loopback", site="transport.fetch")
    MV.commit_attempt(tok2)
    recv = MV.snapshot()[("shuffle.recv", "loopback", "transport.fetch")]
    assert recv["bytes"] == 300


def test_nested_attempt_abort_never_double_moves():
    """The union fetch wraps per-peer retry ladders: an inner abort must
    deduct its bytes from the still-open task-level token, so a later
    task-level abort moves each byte exactly once."""
    outer = MV.begin_attempt()
    inner = MV.begin_attempt()
    MV.record("shuffle.recv", 100, link="loopback", site="s")
    MV.abort_attempt(inner)            # per-peer attempt failed
    inner2 = MV.begin_attempt()
    MV.record("shuffle.recv", 100, link="loopback", site="s")
    MV.commit_attempt(inner2)          # retry succeeded
    MV.abort_attempt(outer)            # then the whole task aborted
    tot = MV.edge_link_totals()
    assert tot[("shuffle.retry", "loopback")]["bytes"] == 200
    recv = tot.get(("shuffle.recv", "loopback"))
    assert recv is None or recv["bytes"] == 0


def test_token_removal_is_by_identity_not_value():
    """Nested tokens start as equal empty dicts and receive identical
    updates in record(), so commit/abort must pop the exact token OBJECT —
    value comparison removes a sibling instead (regression: a peer's retry
    ladder exhausting then the union token aborting drove shuffle.recv
    negative, over-counted shuffle.retry, and leaked a zombie token that
    absorbed every later recv note on the thread)."""
    union = MV.begin_attempt()
    peer = MV.begin_attempt()          # value-equal to union throughout
    MV.record("shuffle.recv", 100, link="loopback", site="s")
    MV.abort_attempt(peer)             # first per-peer attempt failed
    peer2 = MV.begin_attempt()
    MV.record("shuffle.recv", 50, link="loopback", site="s")
    MV.abort_attempt(peer2)            # retry failed too: ladder exhausted
    MV.abort_attempt(union)            # so the whole union fetch aborts
    tot = MV.edge_link_totals()
    assert tot[("shuffle.retry", "loopback")]["bytes"] == 150
    recv = tot.get(("shuffle.recv", "loopback"))
    assert recv is None or recv["bytes"] == 0
    # no zombie token left to absorb this thread's future recv notes
    assert not getattr(MV._tls, "attempts", None)
    MV.record("shuffle.recv", 30, link="loopback", site="s")
    assert MV.edge_link_totals()[("shuffle.recv", "loopback")]["bytes"] == 30


def test_transport_corruption_lands_on_retry_edge():
    """End-to-end over a real TCP fetch: the CRC-failed first attempt's
    wire bytes move to shuffle.retry, the successful retry's payload is
    counted exactly once on shuffle.recv (satellite: deterministic nonzero
    retry-edge bytes from the corrupt fault)."""
    from spark_rapids_tpu.shuffle.fetch import ShuffleFetchIterator
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
    from spark_rapids_tpu.shuffle.transport import TcpTransport
    ShuffleBlockStore.reset()
    store = ShuffleBlockStore.get()
    rng = np.random.default_rng(21)
    t = pa.table({"k": pa.array(rng.integers(0, 50, 200).astype(np.int64)),
                  "v": pa.array(rng.normal(size=200))})
    batch = ColumnarBatch.from_arrow(t)
    sid = store.register_shuffle()
    store.write_block(sid, 0, batch)
    transport = TcpTransport(RapidsConf())
    faults.configure("corrupt:transport.corrupt:1")
    try:
        MV.reset()                     # drop the from_arrow h2d noise
        addr = ("127.0.0.1", transport.port)
        it = ShuffleFetchIterator(
            [lambda: transport.make_client(addr)], sid, 0,
            max_retries=1, retry_backoff_s=0.0)
        fetched = list(it)
        assert len(it.errors) == 1 and "checksum mismatch" in it.errors[0]
        got = fetched[0].to_arrow()
        assert got.to_pylist() == t.to_pylist()
        tot = MV.edge_link_totals()
        retry = tot[("shuffle.retry", "loopback")]
        assert retry["bytes"] > 0              # the corrupted full block
        assert retry["payload_bytes"] == 0     # it never decoded
        recv = tot[("shuffle.recv", "loopback")]
        assert recv["bytes"] > 0
        # payload counted ONCE despite two attempts, in block-store units
        assert recv["payload_bytes"] == \
            sum(b.device_memory_size() for b in fetched)
    finally:
        faults.reset()
        transport.shutdown()
        ShuffleBlockStore.reset()


# -- per-query mirror + read-outs ---------------------------------------------

def test_collector_mirror_and_query_summary():
    col = M.QueryMetricsCollector("mv-test")
    with M.collector_context(col):
        MV.record("shuffle.recv", 1000, link="loopback",
                  site="transport.fetch")
        MV.record("h2d", 400, link="pcie", site="t")
    stats = col.movement_stats()
    assert stats[("shuffle.recv", "loopback")]["bytes"] == 1000
    summ = MV.query_summary(col, result_bytes=700)
    assert summ["total_bytes"] == 1400
    assert summ["edges"]["h2d"]["pcie"]["bytes"] == 400
    assert summ["result_bytes"] == 700
    assert summ["amplification"] == 2.0
    # a query that moved nothing reports no movement section at all
    assert MV.query_summary(M.QueryMetricsCollector("empty")) is None
    # an aborted attempt reclassifies inside the ambient mirror too
    with M.collector_context(col):
        tok = MV.begin_attempt()
        MV.record("shuffle.recv", 50, link="loopback", site="transport.fetch")
        MV.abort_attempt(tok)
    stats = col.movement_stats()
    assert stats[("shuffle.recv", "loopback")]["bytes"] == 1000
    assert stats[("shuffle.retry", "loopback")]["bytes"] == 50
    # the test hook clears the global ledger
    M.reset_observability()
    assert MV.total_bytes() == 0


def test_query_end_movement_section_and_sample(tmp_path):
    """The session action path: query.end carries the movement section with
    an amplification factor, a forced movement.sample flush covers short
    queries, and a no-shuffle local query keeps every network edge at
    exactly zero while still metering h2d."""
    spark = TpuSession({"spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    try:
        t = pa.table({"k": pa.array(np.arange(100, dtype=np.int64)),
                      "v": pa.array(np.arange(100, dtype=np.float64))})
        df = (spark.create_dataframe(t)
              .filter(F.col("k") < F.lit(50)).select("k", "v"))
        out = df.collect()
        assert out.num_rows == 50
    finally:
        EL.shutdown()
    recs = []
    for path in glob.glob(str(tmp_path) + "/events-*.jsonl"):
        with open(path, encoding="utf-8") as f:
            recs += [json.loads(ln) for ln in f if ln.strip()]
    qend = [r for r in recs if r.get("event") == "query.end"]
    assert qend and qend[-1].get("movement"), qend
    mvs = qend[-1]["movement"]
    assert mvs["total_bytes"] > 0
    assert mvs["edges"]["h2d"]["pcie"]["bytes"] > 0
    assert mvs["result_bytes"] == out.nbytes
    assert mvs["amplification"] > 0
    for edge in MV.NETWORK_EDGES:
        assert edge not in mvs["edges"], mvs["edges"]
    samples = [r for r in recs if r.get("event") == "movement.sample"]
    assert samples, "query epilogue did not force a movement.sample flush"
    for fl in samples[-1]["flows"]:
        assert fl["edge"] not in MV.NETWORK_EDGES or fl["bytes"] == 0, fl


# -- capture points -----------------------------------------------------------

def test_arrow_boundary_meters_pcie():
    t = pa.table({"v": pa.array(np.arange(128, dtype=np.float64))})
    b = ColumnarBatch.from_arrow(t)
    sz = b.device_memory_size()
    assert sz > 0
    assert MV.edge_link_totals()[("h2d", "pcie")]["bytes"] == sz
    b.to_arrow()
    assert MV.edge_link_totals()[("d2h", "pcie")]["bytes"] == sz
    # unified with the PR-12 per-node stats meters: one call fed both
    assert M.current_collector() is None   # (global path exercised above)


def test_direct_spill_store_meters_io(tmp_path):
    from spark_rapids_tpu.runtime.direct_spill import DirectSpillStore, ALIGN
    store = DirectSpillStore(str(tmp_path), batch_bytes=1 << 20)
    payload = b"x" * 5000
    try:
        h = store.write(payload)
        assert store.read(h) == payload
    finally:
        store.close()
    snap = MV.snapshot()
    w = snap[("spill.write", "disk", "direct_spill")]
    # physical bytes are the ALIGNED write, payload the logical buffer
    assert w["bytes"] == -(-len(payload) // ALIGN) * ALIGN
    assert w["payload_bytes"] == len(payload)
    assert w["transfers"] == 1 and w["seconds"] >= 0
    r = snap[("spill.read", "disk", "direct_spill")]
    assert r["bytes"] == len(payload)


# -- cluster invariants (the satellites) --------------------------------------

def test_cluster_loopback_never_inflates_tcp(tmp_path):
    """Satellite (misattribution fix): a 2-executor same-host cluster moves
    zero ``tcp`` bytes — transport traffic is ``loopback``, short-circuited
    same-executor fetches are ``local`` with zero network bytes."""
    settings = {
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.movement.sample.intervalBytes": "64k",
    }
    spark = TpuSession()               # driver log stays off: executor-only
    rng = np.random.default_rng(7)
    t = pa.table({"k": pa.array(rng.integers(0, 23, 4000).astype(np.int64)),
                  "v": pa.array(rng.random(4000))})
    df = (spark.create_dataframe(t, num_partitions=4)
          .group_by(F.col("k")).agg(F.sum(F.col("v")).alias("s")))
    with MiniCluster(n_executors=2, conf=RapidsConf(settings),
                     platform="cpu") as c:
        got = c.collect(df)
    assert got.num_rows == 23
    samples, _ = _last_samples(tmp_path)
    assert len(samples) >= 2, f"expected both executor ledgers: {samples}"
    tcp = _flow_sum(samples, "bytes", lambda fl: fl["link"] == "tcp")
    loop = _flow_sum(samples, "bytes", lambda fl: fl["link"] == "loopback")
    local = _flow_sum(samples, "payload_bytes",
                      lambda fl: fl["link"] == "local"
                      and fl["edge"] == "shuffle.recv")
    local_wire = _flow_sum(samples, "bytes",
                           lambda fl: fl["link"] == "local"
                           and fl["edge"] == "shuffle.recv")
    assert tcp == 0, f"same-host cluster inflated the tcp ledger: {tcp}B"
    assert loop > 0, "no loopback transport bytes metered"
    assert local > 0, "no short-circuited local fetches metered"
    assert local_wire == 0, "local short-circuit reported network bytes"


def test_cluster_chaos_ledger_integrity(tmp_path):
    """Satellite (chaos): an executor SIGKILLed at result-task start plus a
    CRC-corrupted fetch still leave shuffle.recv payload ~= the map-output
    bytes the driver registered (no double-count across retries and
    recomputes), with the failed attempt's bytes on the retry edge."""
    rng = np.random.default_rng(11)
    t = pa.table({"k": pa.array(rng.integers(0, 9, 3000).astype(np.int64)),
                  "v": pa.array(rng.random(3000))})
    # expectation BEFORE the event log opens: the driver-local run must not
    # pollute the driver's stage/movement records
    spark = TpuSession()
    df = (spark.create_dataframe(t, num_partitions=4)
          .group_by(F.col("k")).agg(F.sum(F.col("v")).alias("s")))
    exp = {r["k"]: r["s"] for r in df.collect_host().to_pylist()}
    settings = {
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.movement.sample.intervalBytes": "64k",
    }
    TpuSession(settings)               # arms the DRIVER's event log
    chaos = dict(settings)
    chaos["spark.rapids.tpu.test.faults"] = \
        "exec_kill:cluster.result.begin.0:1,corrupt:transport.corrupt:1"
    MV.reset()
    try:
        with MiniCluster(n_executors=2, conf=RapidsConf(chaos),
                         platform="cpu") as c:
            got = {r["k"]: r["s"] for r in c.collect(df).to_pylist()}
    finally:
        EL.shutdown()
    assert set(got) == set(exp)
    for k in exp:
        assert got[k] == pytest.approx(exp[k], rel=1e-9), k
    samples, registered = _last_samples(tmp_path)
    assert registered > 0, "driver log carries no stage.map.end sizes"
    retry = _flow_sum(samples, "bytes",
                      lambda fl: fl["edge"] == "shuffle.retry")
    assert retry > 0, "corrupted fetch left no bytes on the retry edge"
    recv = _flow_sum(samples, "payload_bytes",
                     lambda fl: fl["edge"] == "shuffle.recv")
    cov = recv / registered
    assert 0.85 <= cov <= 1.2, \
        (f"recv payload {recv}B vs registered {registered}B ({cov:.2f}x): "
         f"retries/recomputes double-counted the ledger")
    tcp = _flow_sum(samples, "bytes", lambda fl: fl["link"] == "tcp")
    assert tcp == 0
