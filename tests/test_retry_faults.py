"""Chaos suite: task-scoped OOM retry (split-and-retry) + deterministic
fault injection across memory and shuffle.

Mirrors the reference's RmmRetryIteratorSuite / fault-injection tests built
on RmmSpark.forceRetryOOM / forceSplitAndRetryOOM: injected device OOMs and
transport faults must recover through the retry ladders
(runtime/retry.py, shuffle/fetch.py, exec/exchange.py) to results
bit-identical with a fault-free run, with the recovery visible in the
process-wide resilience counters (runtime/metrics.global_registry) and span
events (runtime/tracing.recent_events)."""

import glob
import os
import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.runtime import faults as F
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import retry as R
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.runtime.memory import (BufferCatalog, DeviceManager,
                                             TierEnum)
from spark_rapids_tpu.runtime.retry import DeviceOomError, SplitAndRetryOom


@pytest.fixture(autouse=True)
def _clean_chaos_state():
    F.reset()
    M.reset_global_registry()
    tracing.clear_events()
    yield
    F.reset()
    M.reset_global_registry()
    tracing.clear_events()


def make_batch(n=100, seed=0):
    r = np.random.default_rng(seed)
    t = pa.table({
        "a": pa.array([None if x % 7 == 0 else int(x)
                       for x in r.integers(0, 1000, n)], pa.int64()),
        "d": pa.array(r.normal(size=n)),
        "s": pa.array([f"w{i % 13}" for i in range(n)]),
    })
    return ColumnarBatch.from_arrow(t), t


# -- fault spec / injector ----------------------------------------------------

def test_fault_spec_grammar():
    entries = F.parse_spec("oom:joins.build:2,transport:fetch:1@3,"
                           "splitoom:agg.update:p0.5")
    assert [(e.kind, e.site, e.count, e.skip, e.prob) for e in entries] == [
        ("oom", "joins.build", 2, 0, None),
        ("transport", "fetch", 1, 3, None),
        ("splitoom", "agg.update", 0, 0, 0.5)]
    for bad in ("oom:x", "nuke:x:1", "oom:x:y", "oom:x:1@"):
        with pytest.raises(ValueError):
            F.parse_spec(bad)


def test_injector_counts_and_skip():
    F.configure("oom:x:2@1,transport:y:1", seed=0)
    F.maybe_inject("oom", "x")                  # skipped (the @1)
    for _ in range(2):
        with pytest.raises(DeviceOomError):
            F.maybe_inject("oom", "x")
    F.maybe_inject("oom", "x")                  # exhausted
    F.maybe_inject("oom", "other-site")         # never armed
    F.maybe_inject("transport", "x")            # kind mismatch
    from spark_rapids_tpu.shuffle.transport import TransportError
    with pytest.raises(TransportError):
        F.maybe_inject("transport", "y")
    assert F.injected_log() == [("oom", "x"), ("oom", "x"),
                                ("transport", "y")]


def test_injector_seeded_probability_is_deterministic():
    def schedule(seed, hits=50):
        F.configure("oom:p.site:p0.3", seed=seed)
        fired = []
        for i in range(hits):
            try:
                F.maybe_inject("oom", "p.site")
                fired.append(False)
            except DeviceOomError:
                fired.append(True)
        return fired

    a, b = schedule(11), schedule(11)
    assert a == b and any(a) and not all(a)
    assert schedule(12) != a


# -- split / retry framework --------------------------------------------------

def test_split_batch_roundtrip_and_floors():
    b, t = make_batch(101)
    halves = R.split_batch(b)
    assert [h.num_rows for h in halves] == [50, 51]
    got = pa.concat_tables([h.to_arrow() for h in halves])
    assert got.to_pylist() == t.to_pylist()
    # byte floor: halves below the floor refuse to split
    assert R.split_batch(b, floor_bytes=b.device_memory_size()) is None
    # row floor
    one, _ = make_batch(1)
    assert R.split_batch(one) is None


def test_with_retry_splits_then_recovers():
    b, t = make_batch(64, seed=3)
    F.configure("oom:site.z:2", seed=0)
    pieces = list(R.with_retry([b], lambda x: x, scope="site.z",
                               split_floor_bytes=1))
    assert [p.num_rows for p in pieces] == [16, 16, 32]
    got = pa.concat_tables([p.to_arrow() for p in pieces])
    assert got.to_pylist() == t.to_pylist()
    snap = M.resilience_snapshot()
    assert snap[M.NUM_OOM_RETRIES] == 2
    assert snap[M.NUM_OOM_SPLIT_RETRIES] == 2
    assert len(tracing.recent_events("oom.retry")) == 2
    assert len(tracing.recent_events("oom.split")) == 2


def test_with_retry_floor_allows_one_spill_retry_then_raises():
    b, _ = make_batch(64)
    F.configure("oom:site.w:99", seed=0)   # every attempt OOMs
    with pytest.raises(DeviceOomError):
        # floor above the batch size: no split possible → one spill-only
        # retry, then re-raise
        list(R.with_retry([b], lambda x: x, scope="site.w",
                          split_floor_bytes=1 << 30))
    assert M.resilience_snapshot()[M.NUM_OOM_SPLIT_RETRIES] == 0
    assert M.resilience_snapshot()[M.NUM_OOM_RETRIES] == 2


def test_split_and_retry_oom_skips_spill_only_retry():
    b, _ = make_batch(64)
    F.configure("splitoom:site.v:99", seed=0)
    with pytest.raises(SplitAndRetryOom):
        list(R.with_retry([b], lambda x: x, scope="site.v",
                          splittable=False))
    # exactly one attempt: SplitAndRetryOom against an unsplittable input
    # must not burn a useless spill-only retry
    assert M.resilience_snapshot()[M.NUM_OOM_RETRIES] == 1


def test_with_retry_max_splits_bound():
    b, _ = make_batch(64)
    F.configure("oom:site.m:99", seed=0)
    with pytest.raises(DeviceOomError):
        list(R.with_retry([b], lambda x: x, scope="site.m",
                          max_splits=2, split_floor_bytes=1))
    assert M.resilience_snapshot()[M.NUM_OOM_SPLIT_RETRIES] == 2


def test_with_restore_on_retry_rolls_back():
    class Acc:
        def __init__(self):
            self.vals = []
            self._ckpt = None

        def checkpoint(self):
            self._ckpt = list(self.vals)

        def restore(self):
            self.vals = list(self._ckpt)

    acc = Acc()
    F.configure("oom:site.r:1", seed=0)
    b, _ = make_batch(16)

    def fn(x):
        with R.with_restore_on_retry(acc):
            acc.vals.append(x.num_rows)   # side effect BEFORE the oom
            F.maybe_inject("oom", "site.r")
            return x.num_rows

    out = list(R.with_retry([b], fn, split_floor_bytes=1))
    # first attempt appended 16 then OOMed → restored; halves re-ran clean
    assert acc.vals == [8, 8] and sum(out) == 16


def test_call_with_retry_spill_only():
    F.configure("oom:site.c:2", seed=0)
    calls = []

    def thunk():
        calls.append(1)
        F.maybe_inject("oom", "site.c")
        return "ok"

    assert R.call_with_retry(thunk) == "ok"
    assert len(calls) == 3
    assert M.resilience_snapshot()[M.NUM_OOM_RETRIES] == 2


# -- strict budget + catalog recovery ----------------------------------------

def test_register_with_retry_splits_oversized_batch():
    b, t = make_batch(256, seed=5)
    cat = BufferCatalog(device_budget=int(b.device_memory_size() * 0.6),
                        host_budget=1 << 30)
    pieces = R.register_with_retry(b, 100.0, catalog=cat, split_floor_bytes=1)
    assert len(pieces) >= 2
    got = pa.concat_tables([p.get_batch().to_arrow() for p in pieces])
    assert got.to_pylist() == t.to_pylist()
    assert M.resilience_snapshot()[M.NUM_OOM_SPLIT_RETRIES] >= 1
    for p in pieces:
        p.close()
    assert cat.num_buffers == 0


def test_spill_for_retry_frees_lower_priority_buffers(tmp_path):
    from spark_rapids_tpu.runtime.memory import (
        OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY)
    b0, _ = make_batch(128, seed=1)
    # budget fits exactly this buffer; the retry spill targets budget//2,
    # so the lower-priority shuffle output must leave the device tier
    cat = BufferCatalog(device_budget=b0.device_memory_size(),
                        host_budget=1 << 30, spill_dir=str(tmp_path))
    bid = cat.add_batch(b0, OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY)
    assert cat.get_tier(bid) == TierEnum.DEVICE
    R._spill_for_retry(cat)
    assert cat.get_tier(bid) != TierEnum.DEVICE
    assert M.resilience_snapshot()[M.OOM_SPILL_BYTES] > 0


# -- operator-level recovery --------------------------------------------------

def _join_plan(conf):
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    from spark_rapids_tpu.exec.joins import HashJoinExec
    from spark_rapids_tpu.expr.core import col
    left = pa.table({"k": pa.array(np.arange(300, dtype=np.int64) % 40),
                     "v": pa.array(np.arange(300, dtype=np.float64))})
    right = pa.table({"k": pa.array(np.arange(40, dtype=np.int64)),
                      "w": pa.array(np.arange(40, dtype=np.int64) * 10)})
    return HashJoinExec("inner", [col("k")], [col("k")],
                        ArrowScanExec([left], batch_rows=64),
                        ArrowScanExec([right]), conf=conf)


def _sorted_rows(table):
    return sorted(table.to_pylist(),
                  key=lambda r: tuple((v is None, v) for v in r.values()))


def test_hash_join_recovers_from_probe_and_build_oom():
    conf = RapidsConf({C.RETRY_SPLIT_FLOOR_BYTES.key: "1b"})
    expect = _sorted_rows(_join_plan(conf).execute_collect())
    F.configure("oom:joins.build:1,oom:joins.gather:2", seed=0)
    got = _sorted_rows(_join_plan(conf).execute_collect())
    assert got == expect
    snap = M.resilience_snapshot()
    assert snap[M.NUM_OOM_RETRIES] == 3
    assert snap[M.NUM_OOM_SPLIT_RETRIES] >= 2   # both gather ooms split
    assert F.injected_log().count(("oom", "joins.gather")) == 2


def test_full_outer_join_matched_acc_restores_under_oom():
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    from spark_rapids_tpu.exec.joins import HashJoinExec
    from spark_rapids_tpu.expr.core import col
    left = pa.table({"k": pa.array([1, 2, 3, 4, 5, 6, 7, 8], pa.int64())})
    right = pa.table({"k": pa.array([2, 4, 6, 8, 10, 12], pa.int64())})

    def run():
        ex = HashJoinExec(
            "fullouter", [col("k")], [col("k")],
            ArrowScanExec([left], batch_rows=4), ArrowScanExec([right]),
            conf=RapidsConf({C.RETRY_SPLIT_FLOOR_BYTES.key: "1b"}))
        return _sorted_rows(ex.execute_collect())

    expect = run()
    F.configure("oom:joins.gather:2", seed=0)
    got = run()
    # unmatched-build rows emitted exactly once despite re-probed attempts
    assert got == expect


def test_aggregate_recovers_from_update_and_merge_oom():
    from spark_rapids_tpu.exec.aggregate import HashAggregateExec
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    from spark_rapids_tpu.expr.core import Alias, col
    from spark_rapids_tpu.expr.aggregates import Sum
    # integer values: int sums are order-independent, so split partials are
    # BIT-identical to the single pass (float sums can drift a ulp when the
    # reduction order changes — same caveat as the reference's
    # variableFloatAgg)
    t = pa.table({"k": pa.array(np.arange(500, dtype=np.int64) % 17),
                  "v": pa.array(
                      np.random.default_rng(0).integers(-1000, 1000, 500))})

    def run():
        ex = HashAggregateExec(
            [col("k")], [Alias(Sum(col("v")), "sv")],
            ArrowScanExec([t], batch_rows=100),
            conf=RapidsConf({C.RETRY_SPLIT_FLOOR_BYTES.key: "1b"}))
        return _sorted_rows(ex.execute_collect())

    expect = run()
    F.configure("oom:agg.update:2,oom:agg.merge:1", seed=0)
    got = run()
    assert got == expect
    snap = M.resilience_snapshot()
    assert snap[M.NUM_OOM_RETRIES] == 3
    assert snap[M.NUM_OOM_SPLIT_RETRIES] == 2
    assert len(F.injected_log()) == 3


def test_sort_recovers_with_spill_only_retry():
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    from spark_rapids_tpu.exec.sort import SortExec
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.ops.sorting import SortOrder
    vals = np.random.default_rng(4).integers(0, 1000, 400)
    t = pa.table({"v": pa.array(vals)})

    def run():
        ex = SortExec([col("v")], [SortOrder()],
                      ArrowScanExec([t], batch_rows=128))
        return ex.execute_collect()["v"].to_pylist()

    expect = run()
    assert expect == sorted(vals.tolist())
    F.configure("oom:sort.sort:1", seed=0)
    assert run() == expect
    assert M.resilience_snapshot()[M.NUM_OOM_RETRIES] == 1


def test_exchange_map_oom_and_fetch_fault_recover():
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
    from spark_rapids_tpu.shuffle.partitioning import HashPartitioner
    t = pa.table({"a": pa.array(np.arange(200, dtype=np.int64)),
                  "b": pa.array([f"x{i % 9}" for i in range(200)])})

    def run():
        ShuffleBlockStore.reset()
        ex = ShuffleExchangeExec(
            HashPartitioner([col("a")], 3), ArrowScanExec([t], batch_rows=64),
            conf=RapidsConf({C.RETRY_SPLIT_FLOOR_BYTES.key: "1b",
                             C.NUM_LOCAL_TASKS.key: 1}))
        return _sorted_rows(ex.execute_collect())

    expect = run()
    F.configure("oom:exchange.map:2,oom:exchange.write:1,transport:fetch:1",
                seed=0)
    got = run()
    assert got == expect
    snap = M.resilience_snapshot()
    assert snap[M.NUM_OOM_SPLIT_RETRIES] >= 2
    assert snap[M.FETCH_RECOMPUTES] == 1
    assert ("transport", "fetch") in F.injected_log()
    assert tracing.recent_events("fetch.recompute")
    ShuffleBlockStore.reset()


# -- shuffle transport / heartbeat error paths --------------------------------

def test_fetch_backoff_is_jittered_exponential_and_capped():
    from spark_rapids_tpu.shuffle.fetch import ShuffleFetchIterator
    it = ShuffleFetchIterator([], 1, 0, retry_backoff_s=0.05,
                              retry_backoff_max_s=0.4)
    delays = [it._backoff(a) for a in range(12)]
    for a, d in enumerate(delays):
        ceiling = min(0.05 * 2 ** a, 0.4)
        assert ceiling / 2 <= d <= ceiling          # jitter in [0.5, 1.0)×
    assert max(delays) <= 0.4                        # hard cap
    # same (shuffle, reduce) → same deterministic jitter schedule
    it2 = ShuffleFetchIterator([], 1, 0, retry_backoff_s=0.05,
                               retry_backoff_max_s=0.4)
    assert [it2._backoff(a) for a in range(12)] == delays


def test_fetch_retry_failover_recompute_counters(tmp_path):
    from spark_rapids_tpu.shuffle.fetch import ShuffleFetchIterator
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
    from spark_rapids_tpu.shuffle.transport import TransportError
    ShuffleBlockStore.reset()
    store = ShuffleBlockStore.get()
    batch, t = make_batch(40, seed=9)
    sid = store.register_shuffle()
    store.write_block(sid, 0, batch)

    class DeadClient:
        def fetch_blocks(self, shuffle_id, reduce_id):
            raise TransportError("peer unreachable")
            yield  # pragma: no cover

    class GoodClient:
        def fetch_blocks(self, shuffle_id, reduce_id):
            yield from store.read_partition(shuffle_id, reduce_id)

    it = ShuffleFetchIterator([DeadClient, GoodClient], sid, 0,
                              max_retries=1, retry_backoff_s=0.0)
    out = [b.to_arrow() for b in it]
    assert len(out) == 1 and out[0].num_rows == 40
    snap = M.resilience_snapshot()
    assert snap[M.FETCH_RETRIES] == 1      # one same-peer retry
    assert snap[M.FETCH_FAILOVERS] == 1    # one failover to the replica

    recomputed = {"n": 0}

    def recompute():
        recomputed["n"] += 1
        yield batch

    it2 = ShuffleFetchIterator([DeadClient], sid, 0, recompute=recompute,
                               max_retries=0, retry_backoff_s=0.0)
    assert len(list(it2)) == 1 and recomputed["n"] == 1
    assert M.resilience_snapshot()[M.FETCH_RECOMPUTES] == 1
    ShuffleBlockStore.reset()


def test_tcp_peer_death_mid_stream_fails_over_without_double_consume():
    """Injected send fault on the server's first data chunk (sends 1-3 are
    the metadata/transfer handshake): the connection dies mid-stream, the
    failing attempt is buffered (never partially emitted), the iterator
    fails over to a healthy factory, and the partition arrives exactly
    once."""
    from spark_rapids_tpu.shuffle.fetch import ShuffleFetchIterator
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
    from spark_rapids_tpu.shuffle.transport import TcpTransport
    ShuffleBlockStore.reset()
    store = ShuffleBlockStore.get()
    batch, t = make_batch(60, seed=11)
    sid = store.register_shuffle()
    store.write_block(sid, 0, batch)
    transport = TcpTransport(RapidsConf())
    try:
        addr = ("127.0.0.1", transport.port)
        # sends: client META_REQ, server META_RESP, client TRANSFER_REQ,
        # then the injected fault kills the server's first BLOCK_CHUNK
        F.configure("transport:transport.send:1@3", seed=0)
        it = ShuffleFetchIterator(
            [lambda: transport.make_client(addr)] * 2, sid, 0,
            max_retries=0, retry_backoff_s=0.0)
        out = [b.to_arrow() for b in it]
        assert len(out) == 1 and out[0].to_pylist() == t.to_pylist()
        assert len(it.errors) == 1
        assert M.resilience_snapshot()[M.FETCH_FAILOVERS] == 1
        assert F.injected_log() == [("transport", "transport.send")]
    finally:
        transport.shutdown()
        ShuffleBlockStore.reset()


def test_tcp_truncated_frame_one_failover():
    """A server advertising full block sizes but sending truncated payloads
    → 'short block' TransportError → exactly one failover to the healthy
    replica, no double-consume."""
    from spark_rapids_tpu.shuffle.fetch import ShuffleFetchIterator
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
    from spark_rapids_tpu.shuffle.transport import (TcpShuffleServer,
                                                    TcpTransport)
    ShuffleBlockStore.reset()
    store = ShuffleBlockStore.get()
    batch, t = make_batch(50, seed=12)
    sid = store.register_shuffle()
    store.write_block(sid, 0, batch)
    transport = TcpTransport(RapidsConf())
    real = TcpShuffleServer.serialized_blocks
    calls = {"n": 0}

    def flaky_blocks(self, shuffle_id, reduce_id):
        blobs = real(self, shuffle_id, reduce_id)
        calls["n"] += 1
        if calls["n"] == 2:
            # call 1 = metadata (full sizes), call 2 = the transfer:
            # truncate the payload so the client's size check trips
            return [b[:-8] for b in blobs]
        return blobs

    TcpShuffleServer.serialized_blocks = flaky_blocks
    try:
        addr = ("127.0.0.1", transport.port)
        it = ShuffleFetchIterator(
            [lambda: transport.make_client(addr)] * 2, sid, 0,
            max_retries=0, retry_backoff_s=0.0)
        out = [b.to_arrow() for b in it]
        assert len(out) == 1 and out[0].to_pylist() == t.to_pylist()
        assert len(it.errors) == 1 and "short block" in it.errors[0]
        assert M.resilience_snapshot()[M.FETCH_FAILOVERS] == 1
    finally:
        TcpShuffleServer.serialized_blocks = real
        transport.shutdown()
        ShuffleBlockStore.reset()


def test_heartbeat_endpoint_survives_transient_manager_failure():
    from spark_rapids_tpu.shuffle.heartbeat import (
        RapidsShuffleHeartbeatEndpoint, RapidsShuffleHeartbeatManager)
    mgr = RapidsShuffleHeartbeatManager(timeout_s=60)
    a = RapidsShuffleHeartbeatEndpoint(mgr, "exec-a", "h1", 1, interval_s=0.01)
    try:
        real = mgr.heartbeat
        fails = {"n": 3}

        def flaky(executor_id):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise ConnectionError("driver unreachable")
            return real(executor_id)

        mgr.heartbeat = flaky
        # the beat loop swallows transient failures and keeps beating
        waiter = threading.Event()
        for _ in range(500):            # up to 5s on a loaded box
            if fails["n"] == 0:
                break
            waiter.wait(0.01)
        assert fails["n"] == 0          # failures were consumed, not fatal
        mgr.register("exec-b", "h2", 2)
        a.beat_now()                    # recovered: learns the new peer
        assert [p.executor_id for p in a.known_peers()] == ["exec-b"]
    finally:
        a.close()


def test_heartbeat_expiry_names_dead_peers_for_invalidation():
    from spark_rapids_tpu.shuffle.heartbeat import (
        RapidsShuffleHeartbeatManager)
    mgr = RapidsShuffleHeartbeatManager(timeout_s=0.03)
    mgr.register("exec-dead", "h", 1)
    mgr.register("exec-live", "h", 2)
    threading.Event().wait(0.05)
    mgr.heartbeat("exec-live")
    dead = mgr.expire_dead()
    assert [p.executor_id for p in dead] == ["exec-dead"]
    assert {p.executor_id for p in mgr.live_peers()} == {"exec-live"}
    with pytest.raises(KeyError):
        mgr.heartbeat("exec-dead")


# -- the acceptance chaos run: TPC-H q18 --------------------------------------

@pytest.fixture(scope="module")
def tpch_paths(tmp_path_factory):
    from spark_rapids_tpu.benchmarks import tpch
    d = tmp_path_factory.mktemp("tpch_chaos")
    return tpch.generate(0.005, str(d))


def _run_q18(paths, extra_conf=None):
    """q18 over explicit per-file scan partitions (multi-partition scans put
    a real ShuffleExchangeExec under the group-by, so the fetch ladder is
    live — directory scans collapse to one partition)."""
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.session import TpuSession
    conf = {C.NUM_LOCAL_TASKS.key: 1}
    conf.update(extra_conf or {})
    spark = TpuSession(conf)
    dfs = {}
    for name, p in paths.items():
        files = sorted(glob.glob(os.path.join(p, "*.parquet"))) or [p]
        dfs[name] = spark.read_parquet(files, files_per_partition=2)
    return tpch.q18(dfs).collect().to_pylist()


def test_q18_chaos_bit_identical(tpch_paths):
    """THE acceptance run: two injected join-build OOMs + one dropped fetch
    still produce results bit-identical with the fault-free run, with ≥2
    splits and ≥1 fetch recovery in the metrics."""
    clean = _run_q18(tpch_paths)
    M.reset_global_registry()
    tracing.clear_events()
    chaos = _run_q18(tpch_paths, {
        C.TEST_FAULTS.key: "oom:joins.build:2,transport:fetch:1",
        C.TEST_FAULTS_SEED.key: 42,
        C.RETRY_SPLIT_FLOOR_BYTES.key: "1b",
    })
    assert chaos == clean
    snap = M.resilience_snapshot()
    assert snap[M.NUM_OOM_SPLIT_RETRIES] >= 2
    assert snap[M.FETCH_RECOMPUTES] + snap[M.FETCH_RETRIES] >= 1
    # the whole configured schedule fired
    log = F.injected_log()
    assert log.count(("oom", "joins.build")) == 2
    assert log.count(("transport", "fetch")) == 1
    F.reset()
    # and with injection disarmed the same query is fault-free again
    M.reset_global_registry()
    assert _run_q18(tpch_paths) == clean
    snap = M.resilience_snapshot()
    assert snap[M.NUM_OOM_RETRIES] == 0 and snap[M.FETCH_RECOMPUTES] == 0
