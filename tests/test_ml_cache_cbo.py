"""ML export, dataframe cache, and cost-based optimizer tests
(reference #41 ColumnarRdd, #42 ParquetCachedBatchSerializer, #13 CBO)."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSession


@pytest.fixture
def spark():
    return TpuSession()


def make_df(spark, n=500, parts=3):
    r = np.random.default_rng(5)
    t = pa.table({
        "f1": pa.array(r.normal(0, 1, n)),
        "f2": pa.array([None if i % 17 == 0 else float(i) for i in range(n)],
                       pa.float64()),
        "label": pa.array((r.random(n) > 0.5).astype(float)),
    })
    return spark.create_dataframe(t, num_partitions=parts), t


def test_columnar_partitions_zero_copy(spark):
    import jax
    from spark_rapids_tpu.ml import columnar_partitions
    df, t = make_df(spark)
    total = 0
    for batch in columnar_partitions(df.filter(F.col("f1") > 0)):
        assert isinstance(batch.column(0).data, jax.Array)  # stays on device
        total += batch.num_rows
    want = sum(1 for v in t.column("f1").to_pylist() if v and v > 0)
    assert total == want


def test_to_feature_matrix(spark):
    from spark_rapids_tpu.ml import to_feature_matrix
    df, t = make_df(spark)
    X, y, mask = to_feature_matrix(df, ["f1", "f2"], "label")
    assert X.shape == (500, 2) and y.shape == (500,) and mask.shape == (500,)
    n_null = sum(1 for v in t.column("f2").to_pylist() if v is None)
    assert int(mask.sum()) == 500 - n_null
    # values round-trip (row order preserved within partitions)
    got = np.asarray(X[:, 1])[np.asarray(mask)]
    want = np.array([v for v in t.column("f2").to_pylist() if v is not None],
                    dtype=np.float32)
    assert sorted(got.tolist()) == pytest.approx(sorted(want.tolist()))


def test_feature_matrix_rejects_strings(spark):
    from spark_rapids_tpu.ml import to_feature_matrix
    df = spark.create_dataframe({"s": pa.array(["a", "b"])})
    with pytest.raises(TypeError, match="string feature"):
        to_feature_matrix(df, ["s"])


@pytest.mark.parametrize("serializer", ["device", "parquet"])
def test_cache_materializes_once(spark, serializer):
    calls = {"n": 0}
    import spark_rapids_tpu.plan.nodes as NN
    orig = NN.ScanNode.execute_host

    df, t = make_df(spark, n=100, parts=2)
    cached = df.with_column("x", F.col("f1") * 2).cache(serializer)
    a = cached.collect()
    b = cached.agg(F.alias(F.count(), "n")).collect()
    assert a.num_rows == 100
    assert b.column("n")[0].as_py() == 100
    # second use must read the cache, not recompute: poison the source
    cached._plan.child.children[0].partitions = [
        pa.table({c: pa.array([], t.schema.field(c).type)
                  for c in t.column_names})]
    c = cached.collect()
    assert c.num_rows == 100
    cached.unpersist()


def test_cbo_pins_small_plans_to_host(spark):
    conf = RapidsConf({"spark.rapids.tpu.sql.optimizer.enabled": "true",
                       "spark.rapids.tpu.sql.optimizer.minRows": "1000"})
    s = TpuSession(conf)
    df = s.create_dataframe({"a": pa.array(range(10), pa.int64())})
    small = df.filter(F.col("a") > 2)
    txt = small.explain()
    assert "cost model" in txt
    assert small.collect().num_rows == 7  # host execution still correct

    big = s.range(100000, num_slices=2).filter(F.col("id") > 2)
    assert "cost model" not in big.explain()


def test_cbo_dual_cost_model_reverts_dispatch_bound_sections(spark):
    """Reference CostBasedOptimizer builds Cpu/Gpu cost models and reverts
    sections where acceleration does not pay; here the device dispatch cost
    dominates a medium plan when cranked up, and a large plan stays on
    device when dispatch is cheap."""
    base = {"spark.rapids.tpu.sql.optimizer.enabled": "true",
            "spark.rapids.tpu.sql.optimizer.minRows": "1"}
    # huge per-dispatch overhead → host wins even at 100k rows
    s1 = TpuSession(RapidsConf({**base,
        "spark.rapids.tpu.sql.optimizer.tpu.dispatchCost": "10.0"}))
    df1 = s1.range(100000, num_slices=2).filter(F.col("id") > 2)
    txt1 = df1.explain()
    assert "cost model: device" in txt1
    assert df1.collect().num_rows == 99997  # host path still correct

    # negligible dispatch cost → device wins at the same size
    s2 = TpuSession(RapidsConf({**base,
        "spark.rapids.tpu.sql.optimizer.tpu.dispatchCost": "1e-9"}))
    df2 = s2.range(100000, num_slices=2).filter(F.col("id") > 2)
    assert "cost model" not in df2.explain()
