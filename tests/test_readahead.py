"""Scan readahead (io/readers.readahead_tables + the filescan wiring):
results must be byte-identical at every queue depth, batches must never
reorder or drop under a slow producer, decode must actually overlap the
consumer, and producer errors must surface at the consumer."""

import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.io.readers import readahead_tables


def _tables(n, rows=100):
    rng = np.random.default_rng(1)
    return [pa.table({"i": pa.array(np.full(rows, k, np.int64)),
                      "v": pa.array(rng.random(rows))})
            for k in range(n)]


def test_readahead_preserves_order_and_content():
    tabs = _tables(7)
    for depth in (0, 1, 4, 100):
        got = list(readahead_tables(iter(tabs), depth))
        assert len(got) == len(tabs)
        for a, b in zip(got, tabs):
            assert a is b  # same objects, same order


def test_readahead_slow_reader_no_reorder_no_drop():
    """Injected slow producer: every item arrives, in order, exactly once —
    and decode of item N+1 overlaps consumption of item N (wall clock well
    under the serial sum)."""
    tabs = _tables(6)
    delay = 0.1

    def slow_gen():
        for t in tabs:
            time.sleep(delay)       # "decode"
            yield t

    t0 = time.perf_counter()
    got = []
    for t in readahead_tables(slow_gen(), depth=2):
        time.sleep(delay)           # "device compute"
        got.append(t)
    wall = time.perf_counter() - t0
    assert [t["i"][0].as_py() for t in got] == list(range(6))
    serial = 2 * delay * len(tabs)
    # overlapped pipeline ≈ serial/2 + one pipeline fill; generous margin
    # for slow CI boxes — the structural guarantee (order/count) is above
    assert wall < serial * 0.85, (wall, serial)


def test_readahead_budget_still_completes():
    """A byte budget far below one table still makes progress (the
    one-staged-table floor) and loses nothing."""
    tabs = _tables(5, rows=1000)
    got = list(readahead_tables(iter(tabs), depth=4, budget_bytes=1))
    assert len(got) == 5


def test_readahead_propagates_errors():
    def bad_gen():
        yield _tables(1)[0]
        raise ValueError("decode exploded")

    it = readahead_tables(bad_gen(), depth=2)
    next(it)
    with pytest.raises(ValueError, match="decode exploded"):
        next(it)


def test_readahead_early_close_stops_producer():
    produced = []

    def gen():
        for t in _tables(50):
            produced.append(1)
            time.sleep(0.01)
            yield t

    it = readahead_tables(gen(), depth=2)
    next(it)
    it.close()
    time.sleep(0.2)
    n = len(produced)
    time.sleep(0.2)
    assert len(produced) == n  # producer thread stopped
    assert n < 50


@pytest.mark.parametrize("depth", [0, 1, 4])
def test_filescan_depth_equivalence(tmp_path, depth):
    """End-to-end scan through the session: every depth yields identical
    values, including the residual-filter path."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.session import TpuSession
    rng = np.random.default_rng(2)
    t = pa.table({
        "k": pa.array(rng.integers(0, 50, 5000).astype(np.int64)),
        "v": pa.array(rng.random(5000)),
    })
    for i in range(4):
        pq.write_table(t.slice(i * 1250, 1250),
                       tmp_path / f"part-{i}.parquet")
    spark = TpuSession({
        "spark.rapids.tpu.sql.scan.readahead.depth": depth})
    df = spark.read_parquet(str(tmp_path))
    out = df.collect()
    assert out.num_rows == 5000
    got = sorted(zip(out["k"].to_pylist(), out["v"].to_pylist()))
    exp = sorted(zip(t["k"].to_pylist(), t["v"].to_pylist()))
    assert got == exp
