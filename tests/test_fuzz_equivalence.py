"""Randomized device/host equivalence fuzzing over the widened generator —
the FuzzerUtils + assert_gpu_and_cpu_are_equal analog (reference
integration_tests data_gen.py + asserts.py:238-382): many seeds, many
expression shapes, every type column, exact or ulp-tolerant comparison."""

import math

import pyarrow as pa
import pytest

from conftest import make_table

import spark_rapids_tpu.functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch
from spark_rapids_tpu.expr.core import EvalContext, bind_references, col, lit
from spark_rapids_tpu.plan.host_eval import eval_host


def both(expr, table):
    b = ColumnarBatch.from_arrow(table)
    e = bind_references(expr, b.schema)
    dev = (e.eval(EvalContext.from_batch(b)).to_vector()
           .to_arrow(b.num_rows).to_pylist())
    schema = T.StructType.from_arrow(table.schema)
    host = eval_host(bind_references(expr, schema), table).to_arrow().to_pylist()
    return dev, host


def check(expr, table, rel=1e-9):
    dev, host = both(expr, table)
    assert len(dev) == len(host)
    for g, e in zip(dev, host):
        if e is None or g is None:
            assert g == e, (expr, g, e)
        elif isinstance(e, float):
            if math.isnan(e):
                assert isinstance(g, float) and math.isnan(g), (expr, g, e)
            else:
                assert g == pytest.approx(e, rel=rel, abs=1e-12), (expr, g, e)
        else:
            assert g == e, (expr, g, e)


# expression shapes exercised per seed: arithmetic/comparison/conditional
# over every column type the generator emits
def _shapes():
    c = col
    return [
        # numeric arithmetic incl. nulls and overflow wrap
        c("i") + c("l"), c("l") * c("i"), c("d") / c("f"),
        c("i") % F.lit(7), -c("l"),
        F.abs(c("d")), F.round(c("d"), 1), F.floor(c("f")), F.ceil(c("d")),
        # comparisons across types
        c("i") < c("l"), c("d") >= c("f"), c("s") == F.lit("apple"),
        c("dt") < F.cast(F.lit("2020-06-01"), T.DATE),
        c("ts") >= F.cast(c("dt"), T.TIMESTAMP),
        # conditionals + null plumbing
        F.if_(c("b"), c("i"), F.lit(0)),
        F.coalesce(c("i"), c("l")),
        F.if_(c("d") > 0, c("d"), -c("d")),
        F.isnull(c("f")), F.isnan(c("d")),
        # strings
        F.upper(c("s")), F.length(c("s")), F.substring(c("s"), 2, 3),
        F.concat(c("s"), F.lit("!")), F.like(c("s"), "%a%"),
        F.lpad(c("s"), 8, "*"),
        # datetime
        F.year(c("dt")), F.month(c("dt")), F.dayofmonth(c("dt")),
        F.year(F.cast(c("ts"), T.DATE)),
        F.date_format(c("dt"), "yyyy-MM-dd"),
        F.add_months(c("dt"), 2), F.trunc(c("dt"), "month"),
        # decimal
        F.cast(c("dec"), T.DOUBLE), F.cast(c("dec"), T.LONG),
        F.abs(c("dec")), c("dec") + c("dec"),
        # casts, both directions
        F.cast(c("i"), T.STRING), F.cast(c("d"), T.STRING),
        F.cast(c("dt"), T.STRING), F.cast(c("i"), T.DOUBLE),
        F.cast(c("l"), T.INT),       # wrapping
        F.cast(c("d"), T.LONG),      # clamping
        # hash
        F.hash(c("i"), c("s"), c("dt")),
        # round-2b surface: half-even rounding, set membership, split
        # extraction, json paths, interval arithmetic, fused maps
        F.bround(c("d")), F.bround(c("i"), -2),
        F.isin(c("i"), {1, 5, None, 40}),
        F.element_at0(F.split(c("s"), "a"), 0),
        F.size(F.split(c("s"), "a", 2)),
        F.get_json_object(F.concat(F.lit('{"k": "'), c("s"), F.lit('"}')),
                          "$.k"),
        F.time_add(c("ts"), F.lit(3600 * 1000000)),
        F.date_add_interval(c("dt"), F.lit(45)),
        F.map_value(F.create_map(F.lit("p"), c("i"), F.lit("q"), c("l")),
                    c("s")),
    ]


@pytest.mark.parametrize("seed", [1, 7, 23, 91])
def test_fuzz_expressions(seed):
    t = make_table(500, seed=seed)
    for expr in _shapes():
        check(expr, t)


def test_fuzz_extreme_values():
    """Boundary values the random generator rarely hits: int extremes,
    denormals, infinities, empty strings, epoch edges."""
    import numpy as np
    t = pa.table({
        "i": pa.array([-2**31, 2**31 - 1, 0, -1, None], pa.int32()),
        "l": pa.array([-2**63, 2**63 - 1, 0, 1, None], pa.int64()),
        "d": pa.array([float("inf"), float("-inf"), float("nan"),
                       1e-300, None]),
        "f": pa.array([3.4e38, -3.4e38, 0.0, None, 1.5], pa.float32()),
        "s": pa.array(["", " ", None, "\t", "0"]),
        "b": pa.array([True, False, None, True, False]),
        "dt": pa.array([-719162, 0, 2932896, None, 1], pa.int32()
                       ).cast(pa.date32()),
        "ts": pa.array([0, -1, None, 253402300799000000, 1], pa.int64()
                       ).cast(pa.timestamp("us", tz="UTC")),
        "dec": pa.array([None if v is None else __import__("decimal").Decimal(v)
                         for v in [None, 0, 1, -1, 10**10]],
                        type=pa.decimal128(12, 0)),
    })
    c = col
    for expr in [c("i") + c("i"),          # wraps at INT_MIN*2
                 c("l") * F.lit(2),        # wraps
                 F.abs(c("i")),
                 F.cast(c("d"), T.LONG),   # inf clamps, nan -> 0
                 F.cast(c("f"), T.DOUBLE),
                 F.length(c("s")),
                 F.year(c("dt")),
                 F.cast(c("dec"), T.DOUBLE),
                 F.hash(c("l"), c("d"))]:
        check(expr, t)


def test_subnormal_hash_documented_divergence():
    """XLA runs DAZ/FTZ: subnormal doubles hash as 0.0 on device (documented
    in docs/compatibility.md) — assert the divergence stays exactly that."""
    t_sub = pa.table({"d": pa.array([5e-324])})
    t_zero = pa.table({"d": pa.array([0.0])})
    dev_sub, _ = both(F.hash(col("d")), t_sub)
    dev_zero, host_zero = both(F.hash(col("d")), t_zero)
    assert dev_sub == dev_zero           # device: subnormal == 0.0
    assert dev_zero == host_zero         # and 0.0 itself is Spark-exact


def test_decimal_cast_edges_match_device():
    """Review regressions: overflow→null (not wrap), to-decimal scaling,
    rescale HALF_UP — host oracle must mirror expr/cast.py exactly."""
    import decimal as _dec
    t = pa.table({
        "big": pa.array([_dec.Decimal("3000000000.00"),
                         _dec.Decimal("-3000000000.00"),
                         _dec.Decimal("12.34"), None],
                        type=pa.decimal128(12, 2)),
        "i": pa.array([5, -7, 2**31 - 1, None], pa.int32()),
        "d": pa.array([1.005, -2.5, float("nan"), 1e30]),
    })
    c = col
    for expr in [F.cast(c("big"), T.INT),            # overflow → null
                 F.cast(c("big"), T.LONG),
                 F.cast(c("i"), T.DecimalType(10, 2)),
                 F.cast(c("big"), T.DecimalType(12, 4)),   # upscale
                 F.cast(c("big"), T.DecimalType(11, 0)),   # HALF_UP downscale
                 F.cast(c("d"), T.DecimalType(10, 2))]:    # nan → null
        check(expr, t)


def test_round_edges_match_device():
    t = pa.table({
        "i": pa.array([2**31 - 1, -2**31, 15, -15, None], pa.int32()),
        "d": pa.array([1e308, -1e308, 2.5, -2.5, None]),
    })
    check(F.round(col("i"), -1), t)   # wraps like device astype
    check(F.round(col("d"), 1), t)    # inf-on-scale stays inf
    check(F.round(col("d"), 0), t)
