"""Pandas-UDF exec family: mapInPandas, grouped applyInPandas, cogrouped
applyInPandas, grouped pandas aggregates.

Reference test role: integration_tests/src/main/python/udf_test.py (the
pandas-udf section) — device results must match an independent pandas
computation, including null keys, empty groups, and multi-partition inputs.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def spark():
    return TpuSession()


def _df(spark, n=40, parts=3):
    rng = np.random.default_rng(7)
    k = rng.integers(0, 5, n).astype(np.int64)
    v = np.round(rng.uniform(-10, 10, n), 3)
    key = [None if i % 11 == 10 else int(x) for i, x in enumerate(k)]
    tbl = pa.table({"k": pa.array(key, pa.int64()), "v": pa.array(v)})
    return spark.create_dataframe(tbl).repartition(parts), tbl


def _sorted(rows):
    def norm(x):
        if x is None or (isinstance(x, float) and x != x):
            return (1, 0.0)
        return (0, x)
    return sorted((tuple(norm(x) for x in r) for r in rows))


def test_map_in_pandas(spark):
    df, tbl = _df(spark)

    def doubler(it):
        for pdf in it:
            out = pdf.copy()
            out["v"] = out["v"] * 2.0
            yield out

    got = df.map_in_pandas(doubler, [("k", T.LONG), ("v", T.DOUBLE)]).collect()
    exp = tbl.to_pandas()
    exp["v"] = exp["v"] * 2.0
    assert _sorted(map(tuple, got.to_pandas().itertuples(index=False))) == \
        _sorted(map(tuple, exp.itertuples(index=False)))


def test_map_in_pandas_stateful_iterator(spark):
    """fn sees the WHOLE partition as an iterator — cross-batch state works
    (Spark's iterator contract)."""
    df, _ = _df(spark, parts=2)

    def running(it):
        total = 0.0
        n = 0
        for pdf in it:
            total += float(pdf["v"].sum())
            n += len(pdf)
        yield pd.DataFrame({"total": [total], "n": [n]})

    got = df.map_in_pandas(
        running, [("total", T.DOUBLE), ("n", T.LONG)]).collect()
    # one row per partition; totals over all partitions == global
    assert got.num_rows == 2
    assert sum(got.column("n").to_pylist()) == 40


def test_grouped_apply_in_pandas(spark):
    df, tbl = _df(spark)

    def center(pdf):
        out = pdf.copy()
        out["v"] = out["v"] - out["v"].mean()
        return out

    got = (df.group_by("k").apply_in_pandas(
        center, [("k", T.LONG), ("v", T.DOUBLE)])).collect().to_pandas()
    exp_parts = []
    for _, g in tbl.to_pandas().groupby("k", dropna=False, sort=False):
        gg = g.copy()
        gg["v"] = gg["v"] - gg["v"].mean()
        exp_parts.append(gg)
    exp = pd.concat(exp_parts)
    gs = got.sort_values(["k", "v"], na_position="last").reset_index(drop=True)
    es = exp.sort_values(["k", "v"], na_position="last").reset_index(drop=True)
    assert np.allclose(gs["v"].to_numpy(), es["v"].to_numpy(), atol=1e-9)
    assert gs["k"].fillna(-1).tolist() == es["k"].fillna(-1).tolist()


def test_grouped_apply_includes_null_keys(spark):
    df, tbl = _df(spark)
    got = (df.group_by("k").apply_in_pandas(
        lambda pdf: pd.DataFrame({"k": [pdf["k"].iloc[0]],
                                  "c": [len(pdf)]}),
        [("k", T.LONG), ("c", T.LONG)])).collect().to_pandas()
    exp = (tbl.to_pandas().groupby("k", dropna=False).size())
    assert int(got["c"].sum()) == 40
    null_rows = got[got["k"].isna()]
    assert len(null_rows) == 1  # null keys form one group


def test_cogrouped_apply_in_pandas(spark):
    t1 = pa.table({"k": pa.array([1, 1, 2, 3], pa.int64()),
                   "a": pa.array([1.0, 2.0, 3.0, 4.0])})
    t2 = pa.table({"k": pa.array([1, 2, 2, 4], pa.int64()),
                   "b": pa.array([10.0, 20.0, 30.0, 40.0])})
    d1 = spark.create_dataframe(t1).repartition(2)
    d2 = spark.create_dataframe(t2).repartition(3)

    def summarize(l, r):
        k = l["k"].iloc[0] if len(l) else r["k"].iloc[0]
        return pd.DataFrame({"k": [k], "sa": [float(l["a"].sum())],
                             "sb": [float(r["b"].sum())]})

    got = (d1.group_by("k").cogroup(d2.group_by("k"))
           .apply_in_pandas(summarize, [("k", T.LONG), ("sa", T.DOUBLE),
                                        ("sb", T.DOUBLE)])
           ).collect().to_pandas().sort_values("k").reset_index(drop=True)
    assert got["k"].tolist() == [1, 2, 3, 4]
    assert got["sa"].tolist() == [3.0, 3.0, 4.0, 0.0]
    assert got["sb"].tolist() == [10.0, 50.0, 0.0, 40.0]


def test_pandas_agg_udf(spark):
    df, tbl = _df(spark)
    spread = F.pandas_agg_udf(lambda s: float(s.max() - s.min()), T.DOUBLE)
    got = (df.group_by("k").agg(spread("v").alias("spread"))
           ).collect().to_pandas()
    exp = (tbl.to_pandas().groupby("k", dropna=False)["v"]
           .agg(lambda s: float(s.max() - s.min())))
    gm = {(-1 if pd.isna(r["k"]) else int(r["k"])): r["spread"]
          for _, r in got.iterrows()}
    em = {(-1 if pd.isna(k) else int(k)): v for k, v in exp.items()}
    assert set(gm) == set(em)
    for k in em:
        assert abs(gm[k] - em[k]) < 1e-9


def test_pandas_agg_udf_cannot_mix(spark):
    df, _ = _df(spark)
    spread = F.pandas_agg_udf(lambda s: float(s.max()), T.DOUBLE)
    with pytest.raises(ValueError, match="mix"):
        df.group_by("k").agg(spread("v").alias("a"),
                             F.sum(F.col("v")).alias("b"))


def test_host_fallback_matches_device(spark):
    """collect_host (pure-host plan interpreter) agrees with the exec path."""
    df, _ = _df(spark)

    def center(pdf):
        out = pdf.copy()
        out["v"] = out["v"] - out["v"].mean()
        return out

    plan = df.group_by("k").apply_in_pandas(
        center, [("k", T.LONG), ("v", T.DOUBLE)])
    dev = plan.collect().to_pandas().sort_values(
        ["k", "v"], na_position="last").reset_index(drop=True)
    host = plan.collect_host().to_pandas().sort_values(
        ["k", "v"], na_position="last").reset_index(drop=True)
    assert np.allclose(dev["v"].to_numpy(), host["v"].to_numpy(), atol=1e-9)
