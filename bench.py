"""Benchmark: TPC-H q1/q3/q5 end-to-end through the session API.

BASELINE.md config-2 (TPC-H SF0.1+ scan+filter+agg+join on one TPU VM),
replacing round-1's synthetic fused stage. Each query runs end-to-end
(parquet scan → device pipeline → collect) and is first CHECKED against an
independent single-core NumPy oracle (benchmarks/tpch.py) — a wrong answer
reports value 0 rather than a throughput. Prints ONE JSON line:

  value       = geomean over q1/q3/q5 of (lineitem rows / hot-run seconds), Mrows/s
  vs_baseline = geomean over queries of (numpy oracle E2E time / hot-run time),
                where the oracle re-reads the query's parquet tables per run —
                both sides pay the scan (VERDICT r4 next #2: the old preloaded-
                array oracle capped q3/q5 at the decode floor). The reference's
                own claim is 3x-7x vs CPU Spark, docs/FAQ.md:82-88.
  vs_baseline_compute = the round-4-and-earlier denominator (oracle computes on
                preloaded arrays; engine still pays its scan), kept one round
                for continuity.

Resilience (round-1 postmortem + round-2 tunnel-wedge postmortem): the
measurement runs in a CHILD process with a timeout; the parent probes the
backend first with a SHORT timeout (a wedged tunnel hangs even trivial adds —
see .claude/skills/verify/SKILL.md), retries once, falls back to the CPU
platform if the accelerator never comes up, and ALWAYS prints exactly one
JSON line and exits 0.
"""

import contextlib
import json
import math
import os
import statistics
import subprocess
import sys
import time

TPCH_SF = float(os.environ.get("TPCH_SF", "0.1"))
DATA_DIR = os.environ.get("TPCH_DIR", f"/tmp/tpch_sf{TPCH_SF}")
CHILD_TIMEOUT_S = 2400
PROBE_TIMEOUT_S = 240   # first TPU compile/init can take ~40s; be generous
# statistically honest measurement (VERDICT r5 weak #1: run-to-run variance
# was comparable to a round's progress): every timed section runs BENCH_REPS
# times, the metric is the MEDIAN, and the relative spread (max-min)/median
# is reported per query; a spread past BENCH_MAX_SPREAD marks the line
# degraded so a noisy box can't mint a quiet number
BENCH_REPS = int(os.environ.get("BENCH_REPS", "5"))
BENCH_MAX_SPREAD = float(os.environ.get("BENCH_MAX_SPREAD", "0.5"))
# the background TPU watcher probes the backend on a timer; its subprocess
# competes with timed sections on small boxes (r5 memory notes: background
# work doubled timings). Timed sections hold this pause file; the watcher
# skips probing while it exists and is fresh (tools/tpu_watcher.py).
PAUSE_FILE = os.environ.get("SRT_BENCH_PAUSE_FILE", "/tmp/srt_bench_pause")


@contextlib.contextmanager
def watcher_paused():
    try:
        with open(PAUSE_FILE, "w") as f:
            f.write(str(os.getpid()))
    except OSError:
        pass
    try:
        yield
    finally:
        try:
            os.unlink(PAUSE_FILE)
        except OSError:
            pass


def _check_q1(got, exp):
    assert len(got) == len(exp), (len(got), len(exp))
    for g_, e in zip(got, exp):
        g = list(g_.values())
        assert g[0] == e[0] and g[1] == e[1], (g, e)
        for a, b in zip(g[2:], e[2:]):
            assert abs(a - b) <= 1e-6 * max(1.0, abs(b)), (g, e)


def _check_q3(got, exp):
    assert len(got) == len(exp), (len(got), len(exp))
    for g, (k, d, p, rev) in zip(got, exp):
        assert g["l_orderkey"] == k, (g, k)
        assert abs(g["revenue"] - rev) <= 1e-6 * max(1.0, abs(rev))


def _check_q5(got, exp):
    assert len(got) == len(exp), (len(got), len(exp))
    for g, (n, v) in zip(got, exp):
        assert g["n_name"] == n, (g, n)
        assert abs(g["revenue"] - v) <= 1e-6 * max(1.0, abs(v))


def _check_q18(got, exp):
    import datetime
    assert len(got) == len(exp), (len(got), len(exp))
    epoch = datetime.date(1970, 1, 1)
    for g, (c, o, d, t, s) in zip(got, exp):
        assert g["c_custkey"] == c and g["o_orderkey"] == o, (g, (c, o))
        gd = g["o_orderdate"]
        if isinstance(gd, datetime.date):
            gd = (gd - epoch).days
        assert gd == d, (gd, d)
        assert abs(g["o_totalprice"] - t) <= 1e-6 * max(1.0, abs(t))
        assert abs(g["sum_qty"] - s) <= 1e-6 * max(1.0, abs(s))


CHECKS = {"q1": _check_q1, "q3": _check_q3, "q5": _check_q5,
          "q18": _check_q18}
NP_QUERIES = {"q1": "np_q1", "q3": "np_q3", "q5": "np_q5", "q18": "np_q18"}
# (table -> columns) each query scans — the fair oracle re-reads exactly
# these per run, mirroring what the engine's COLUMN-PRUNED plan scans every
# collect() (plan/pruning.py narrows the FileScanNode the same way)
Q_TABLES = {
    "q1": {"lineitem": ["l_discount", "l_extendedprice", "l_linestatus",
                        "l_quantity", "l_returnflag", "l_shipdate", "l_tax"]},
    "q3": {"customer": ["c_custkey", "c_mktsegment"],
           "orders": ["o_custkey", "o_orderdate", "o_orderkey",
                      "o_shippriority"],
           "lineitem": ["l_discount", "l_extendedprice", "l_orderkey",
                        "l_shipdate"]},
    "q5": {"customer": ["c_custkey", "c_nationkey"],
           "orders": ["o_custkey", "o_orderdate", "o_orderkey"],
           "lineitem": ["l_discount", "l_extendedprice", "l_orderkey",
                        "l_suppkey"],
           "supplier": ["s_nationkey", "s_suppkey"],
           "nation": ["n_name", "n_nationkey", "n_regionkey"],
           "region": ["r_name", "r_regionkey"]},
    "q18": {"customer": ["c_custkey"],
            "orders": ["o_custkey", "o_orderdate", "o_orderkey",
                       "o_totalprice"],
            "lineitem": ["l_orderkey", "l_quantity"]},
}


def _h2d_sites():
    """h2d bytes by metering SITE from the global movement ledger (the
    per-query collector mirror aggregates by link only)."""
    from spark_rapids_tpu.runtime import movement as MV
    out: dict = {}
    for (edge, link, site), rec in MV.snapshot().items():
        if edge == "h2d":
            out[site] = out.get(site, 0) + rec["bytes"]
    return out


def child_main():
    """Measured run; prints the JSON line on success. Runs in a subprocess so a
    wedged tunnel or backend crash cannot take down the parent."""
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the axon site hook re-selects TPU regardless of env; override it
        jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: repeated bench runs (and the driver's
    # end-of-round run) must not re-pay every remote TPU compile
    from __graft_entry__ import _enable_compile_cache
    _enable_compile_cache()
    import spark_rapids_tpu  # noqa: F401  (enables x64)
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.session import TpuSession

    platform = jax.devices()[0].platform
    paths = tpch.generate(TPCH_SF, DATA_DIR)
    # COALESCING stitches the per-partition files into few large batches —
    # fewer per-batch fixed costs; measured fastest on both backends at this
    # scale (docs/tuning.md; reference COALESCING reader role).
    # SRT_PIPELINE=0 disables the pipelined executor for A/B runs (the ci.sh
    # pipeline gate and perf_notes round-7 use this switch).
    # SRT_STAGE_FUSION=0 likewise disables whole-stage fusion (the ci.sh
    # fusion gate compares dispatch counts and bit-identity across the two).
    pipeline_on = os.environ.get("SRT_PIPELINE", "1") == "1"
    fusion_on = os.environ.get("SRT_STAGE_FUSION", "1") == "1"
    spark = TpuSession({
        "spark.rapids.tpu.sql.format.parquet.reader.type": "COALESCING",
        "spark.rapids.tpu.pipeline.enabled": pipeline_on,
        "spark.rapids.tpu.sql.stageFusion.enabled": fusion_on})
    dfs = tpch.load(spark, paths, files_per_partition=4)
    tb = tpch.load_np(paths)
    n_lineitem = len(tb["lineitem"]["l_orderkey"])

    from spark_rapids_tpu.benchmarks.common import read_np

    speedups_e2e, speedups_compute, mrows = [], [], []
    per_query, spreads = {}, []
    with watcher_paused():
        for name, q in tpch.QUERIES.items():
            df = q(dfs)
            res = df.collect()                  # warm (compiles cached after)
            got = res.to_pylist()
            exp = getattr(tpch, NP_QUERIES[name])(tb)
            CHECKS[name](got, exp)              # wrong answer → no number
            # per-SITE h2d split (global ledger delta over the timed reps,
            # averaged back to one rep): the per-query collector mirror has
            # no site dimension, and the encoded-upload win is precisely the
            # scan.encoded-vs-scan.device split (tools/bench_compare.py)
            site0 = _h2d_sites()
            ts = []
            for _ in range(BENCH_REPS):
                t0 = time.perf_counter()
                df.collect()
                ts.append(time.perf_counter() - t0)
            site_delta = {
                k: (v - site0.get(k, 0)) // BENCH_REPS
                for k, v in _h2d_sites().items() if v - site0.get(k, 0) > 0}
            eng = statistics.median(ts)
            spread = (max(ts) - min(ts)) / eng if eng > 0 else 0.0
            # fair oracle: re-read this query's tables from parquet +
            # compute, same rep count (both sides pay the scan; OS page
            # cache is warm for both)
            np_ts = []
            for _ in range(BENCH_REPS):
                t0 = time.perf_counter()
                tb_q = {t: read_np(paths[t], columns=cols)
                        for t, cols in Q_TABLES[name].items()}
                getattr(tpch, NP_QUERIES[name])(tb_q)
                np_ts.append(time.perf_counter() - t0)
                del tb_q
            np_e2e = statistics.median(np_ts)
            # legacy denominator: oracle computes on preloaded arrays
            t0 = time.perf_counter()
            getattr(tpch, NP_QUERIES[name])(tb)
            np_compute = time.perf_counter() - t0
            speedups_e2e.append(np_e2e / eng)
            speedups_compute.append(np_compute / eng)
            mrows.append(n_lineitem / eng / 1e6)
            spreads.append(spread)
            per_query[name] = {
                "engine_s": round(eng, 4), "spread": round(spread, 3),
                "oracle_e2e_s": round(np_e2e, 4),
                "vs_baseline": round(np_e2e / eng, 3),
            }
            # per-operator attribution (query observability collector): the
            # last timed rep's self-time breakdown, so BENCH_*.json
            # trajectories are attributable to operators, not whole queries
            qm = spark.last_query_metrics()
            if qm is not None:
                # retrace denominator: the last timed rep runs hot, so a
                # healthy compile cache shows compiles == 0 here while
                # dispatches stays O(batches) (ROADMAP item 1's gate input)
                cm = qm.compile_metrics()
                per_query[name]["compiles"] = cm["compiles"]
                per_query[name]["dispatches"] = cm["dispatches"]
                ops = []
                queue_stall_ns = 0
                for n in qm.node_summaries():
                    if n["id"] is None:
                        continue
                    m = n["metrics"]
                    self_s = m.get("selfTime", 0) / 1e9
                    build_s = m.get("buildSelfTime", 0) / 1e9
                    # pipeline queue stall total (consumer wait, all edges)
                    queue_stall_ns += sum(
                        v for k, v in m.items()
                        if k.startswith("queueWaitTime:"))
                    ops.append({"op": f"{n['name']}#{n['id']}",
                                "self_s": round(self_s, 4),
                                "rows": m.get("numOutputRows")})
                    if build_s > 0:
                        ops.append({"op": f"{n['name']}#{n['id']} (build)",
                                    "self_s": round(build_s, 4)})
                ops.sort(key=lambda r: -r["self_s"])
                total_self = sum(r["self_s"] for r in ops)
                per_query[name]["operators"] = ops[:8]
                per_query[name]["op_coverage"] = (
                    round(total_self / qm.wall_s, 3) if qm.wall_s else None)
                per_query[name]["queue_stall_s"] = round(
                    queue_stall_ns / 1e9, 4)
                # memory trajectory (allocation-site heap profiler): BENCH
                # files record the hot rep's device high-water mark and who
                # owned it, not just throughput
                msum = qm.memory or {}
                if msum:
                    per_query[name]["peak_device_bytes"] = \
                        msum.get("peak_device_bytes", 0)
                    msites = msum.get("sites") or {}
                    if msites:
                        per_query[name]["top_alloc_site"] = max(
                            msites.items(),
                            key=lambda kv: kv[1].get("peak_bytes", 0))[0]
                # statistics plane (runtime/stats.py): how far the admission
                # estimate was from the hot rep's observed peak, and whether
                # the plan-history store primed it — trajectories of
                # estimate_error show the history store learning a workload
                stats = qm.stats or {}
                if stats.get("estimate_error") is not None:
                    per_query[name]["estimate_error"] = \
                        stats["estimate_error"]
                if stats:
                    per_query[name]["history_hit"] = \
                        bool(stats.get("history_hit"))
                # movement plane (runtime/movement.py): the hot rep's
                # boundary-crossing bytes by link class — BENCH trajectories
                # catch a change that silently starts moving more data, not
                # just one that slows down
                mstats = qm.movement_stats()
                if mstats:
                    def _mv(pred):
                        return sum(v["bytes"] for k, v in mstats.items()
                                   if pred(*k))
                    total_moved = sum(v["bytes"] for v in mstats.values())
                    per_query[name]["movement"] = {
                        "tcp_bytes": _mv(lambda e, lk: lk == "tcp"),
                        "loopback_bytes": _mv(
                            lambda e, lk: lk == "loopback"),
                        "h2d_bytes": _mv(lambda e, lk: e == "h2d"),
                        "d2h_bytes": _mv(lambda e, lk: e == "d2h"),
                        "spill_io_bytes": _mv(
                            lambda e, lk: e.startswith("spill.")),
                        "movement_amplification": (
                            round(total_moved / res.nbytes, 3)
                            if res.nbytes else None),
                        "h2d_sites": site_delta,
                    }

    # resilience counters (retry/split/fetch-failover totals across the
    # whole ladder run): with faults disabled these must be zero — a later
    # round seeing nonzero values here caught a real robustness regression
    from spark_rapids_tpu.runtime import fuse as rfuse
    from spark_rapids_tpu.runtime import metrics as rmetrics
    resilience = rmetrics.resilience_snapshot()
    compile_totals = rfuse.stage_metrics()

    geo = lambda xs: math.exp(sum(math.log(x) for x in xs) / len(xs))
    qnames = "".join(tpch.QUERIES)
    line = {
        "metric": f"tpch_sf{TPCH_SF}_{qnames}_geomean",
        "value": round(geo(mrows), 3),
        "unit": "Mrows/s",
        "vs_baseline": round(geo(speedups_e2e), 3),
        "vs_baseline_compute": round(geo(speedups_compute), 3),
        "baseline_denominator": "numpy-oracle e2e (per-query parquet re-read)",
        "reps": BENCH_REPS,
        "stat": "median",
        "pipeline": pipeline_on,
        "fusion": fusion_on,
        "spread": round(max(spreads), 3),
        "variance_ok": max(spreads) <= BENCH_MAX_SPREAD,
        "queries": per_query,
        "resilience": resilience,
        # whole-process XLA compile/dispatch totals (runtime/fuse.py);
        # per-query hot-rep deltas live in queries.<q>.compiles/dispatches
        "compiles": compile_totals["traces"],
        "dispatches": compile_totals["dispatches"],
    }
    if not line["variance_ok"]:
        line["degraded"] = (f"spread {line['spread']} exceeds "
                            f"{BENCH_MAX_SPREAD}")
    if platform != "tpu":
        line["degraded"] = (line.get("degraded", "") +
                            f" platform={platform}").strip()
    if os.environ.get("BENCH_JOIN_MICRO", "1") == "1":
        try:
            with watcher_paused():
                line["join_microbench"] = join_microbench(smoke=True)
        except Exception as e:  # noqa: BLE001 — secondary must not kill line
            line["join_microbench"] = {"error": repr(e)[:200]}
    # secondary metric: the 22-query TPC-DS sweep at small scale (breadth —
    # window/decimal/basket shapes; reference qa_nightly role). Failures
    # never take down the primary metric. Default OFF on the real chip: the
    # sweep's compile volume could eat the child budget, and a timeout kill
    # mid-dispatch wedges the tunnel (docs/perf_notes.md).
    default_secondary = "1" if platform != "tpu" else "0"
    if os.environ.get("TPCDS_SECONDARY", default_secondary) == "1":
        # shared setup: a failure here is reported as THE error for both
        # sweeps (not a downstream NameError masking the real cause)
        try:
            from spark_rapids_tpu.benchmarks import tpcds
            sf = float(os.environ.get("TPCDS_SF", "0.01"))
            dpaths = tpcds.generate(sf, os.environ.get(
                "TPCDS_DIR", f"/tmp/tpcds_sf{sf}"))
            ddfs = tpcds.load(spark, dpaths)
            dtb = tpcds.load_np(dpaths)
        except Exception as e:  # noqa: BLE001
            line["secondary"] = {"error": repr(e)[:200]}
            line["sql_suite"] = {"error": repr(e)[:200]}
            print(json.dumps(line))
            return
        try:
            # wall_s times ENGINE execution only (plan + collect); the
            # oracle evaluation and value check run off the clock
            wall = 0.0
            results = []
            for qname, q in tpcds.QUERIES.items():
                t0 = time.perf_counter()
                got = [tuple(r.values())
                       for r in q(ddfs).collect().to_pylist()]
                wall += time.perf_counter() - t0
                results.append((qname, got))
            n_ok, failed = 0, []
            for qname, got in results:
                exp = [tuple(r) for r in tpcds.NP_QUERIES[qname](dtb)]
                try:
                    # full value equality (exact + per-column float approx),
                    # same check as tests/test_tpcds.py
                    tpcds.check_rows(got, exp, tpcds.FLOAT_COLS[qname])
                    n_ok += 1
                except Exception:  # noqa: BLE001 — one bad query must not
                    failed.append(qname)  # void the other 21 results
            line["secondary"] = {
                "metric": f"tpcds_sf{sf}_22q_sweep",
                "queries_ok": n_ok, "queries_total": len(tpcds.QUERIES),
                "check": "value-equality",
                "wall_s": round(wall, 2),
            }
            if failed:
                line["secondary"]["failed"] = failed
        except Exception as e:  # noqa: BLE001 — secondary must not kill primary
            line["secondary"] = {"error": repr(e)[:200]}
        try:
            # the official-SQL-text suite through session.sql() — the
            # reference's qa_nightly_sql.py role, value-checked. Every
            # query runs under its own try: one engine error records that
            # query as failed without voiding the rest (or the DataFrame
            # sweep above, which has its own handler).
            from spark_rapids_tpu.sql.tpcds_queries import SQL_QUERIES
            oracles = tpcds.sql_suite_oracles()
            wall = 0.0
            results = []
            n_ok, failed = 0, []
            for qname in sorted(SQL_QUERIES, key=lambda q: int(q[1:])):
                try:
                    t0 = time.perf_counter()
                    got = [tuple(r.values())
                           for r in spark.sql(SQL_QUERIES[qname])
                           .collect().to_pylist()]
                    wall += time.perf_counter() - t0
                    results.append((qname, got))
                except Exception:  # noqa: BLE001
                    failed.append(qname)
            for qname, got in results:       # checks run off the clock
                oracle, float_cols = oracles[qname]
                try:
                    tpcds.check_rows(got, [tuple(r) for r in oracle(dtb)],
                                     float_cols)
                    n_ok += 1
                except Exception:  # noqa: BLE001
                    failed.append(qname)
            line["sql_suite"] = {
                "metric": f"tpcds_sf{sf}_{len(SQL_QUERIES)}q_sql_sweep",
                "queries_ok": n_ok, "queries_total": len(SQL_QUERIES),
                "check": "value-equality",
                "wall_s": round(wall, 2),
            }
            if failed:
                line["sql_suite"]["failed"] = failed
        except Exception as e:  # noqa: BLE001
            line["sql_suite"] = {"error": repr(e)[:200]}
    print(json.dumps(line))


def join_microbench(smoke: bool = False):
    """Kernel-level join-spine microbench: the same unique-int-key probe
    through three formulations, value-checked against each other before any
    timing —

      - ``pallas``: hash_join_build + hash_join_probe
        (ops/pallas_kernels.py; interpret-mode off-TPU, Mosaic on chip)
      - ``searchsorted``: sorted build + two searchsorted (the engine's
        fast-path probe, exec/joins._probe_batch_fast mode "two")
      - ``laxsort_rank``: join_ranks + probe (the general rank path —
        ops/joining.py; the multi-key `lax.sort` spine)

    Median-of-reps wall per formulation, in ms. Smoke mode (ci.sh gate)
    shrinks the data so the check runs in seconds."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import spark_rapids_tpu  # noqa: F401  (x64)
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.expr.core import Col
    from spark_rapids_tpu.ops import joining as J
    from spark_rapids_tpu.ops import pallas_kernels as PK

    n_build = 4096 if smoke else 16384
    n_stream = (1 << 14) if smoke else (1 << 20)
    reps = 3 if smoke else 5
    rng = np.random.default_rng(20260804)
    bk = rng.permutation(
        np.arange(1, 8 * n_build + 1, 8)[:n_build]).astype(np.int64)
    sk = np.concatenate([
        rng.choice(bk, n_stream // 2),
        rng.integers(0, 8 * n_build, n_stream - n_stream // 2),
    ]).astype(np.int64)
    bkj, skj = jnp.asarray(bk), jnp.asarray(sk)
    b_valid = jnp.ones((n_build,), jnp.bool_)
    H = PK.hash_join_buckets(n_build)

    # production shape (exec/joins._JoinCore): the build preps ONCE per
    # join, the probe runs per stream batch, and the rank path re-sorts
    # build+stream per batch — so prep is timed separately and the parity
    # comparison is per-batch probe cost
    @jax.jit
    def f_pallas_build(bkj):
        return PK.hash_join_build(bkj, b_valid, H)

    @jax.jit
    def f_pallas_probe(tk, tr, skj):
        pos, found = PK.hash_join_probe(tk, tr, skj, H)
        return jnp.sum(found.astype(jnp.int64)), pos, found

    @jax.jit
    def f_ss_build(bkj):
        return jax.lax.sort(bkj)

    @jax.jit
    def f_ss_probe(s, skj):
        lo = jnp.searchsorted(s, skj, side="left")
        hi = jnp.searchsorted(s, skj, side="right")
        return jnp.sum((hi - lo).astype(jnp.int64))

    @jax.jit
    def f_rank(bkj, skj):
        bcol = Col(bkj, b_valid, T.LONG)
        scol = Col(skj, jnp.ones((n_stream,), jnp.bool_), T.LONG)
        b_ranks, s_ranks = J.join_ranks([bcol], n_build, n_build,
                                        [scol], n_stream, n_stream)
        _, lo, hi = J.probe(b_ranks, s_ranks)
        return jnp.sum((hi - lo).astype(jnp.int64))

    # value check once, off the clock: all three agree on the match count,
    # and every pallas hit points at a build row holding the probed key
    tk, tr, ok = jax.block_until_ready(f_pallas_build(bkj))
    assert bool(ok), "hash build refused unique keys"
    m_pallas, pos, found = jax.block_until_ready(f_pallas_probe(tk, tr, skj))
    sorted_bk = jax.block_until_ready(f_ss_build(bkj))
    m_ss = int(f_ss_probe(sorted_bk, skj))
    m_rank = int(f_rank(bkj, skj))
    assert int(m_pallas) == m_ss == m_rank, (int(m_pallas), m_ss, m_rank)
    pos_h, found_h = np.asarray(pos), np.asarray(found)
    assert (bk[pos_h[found_h]] == sk[found_h]).all()

    def timed(f, *args):
        jax.block_until_ready(f(*args))
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(f(*args))
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts) * 1000

    pallas_build_ms = timed(f_pallas_build, bkj)
    pallas_ms = timed(f_pallas_probe, tk, tr, skj)
    ss_build_ms = timed(f_ss_build, bkj)
    ss_ms = timed(f_ss_probe, sorted_bk, skj)
    rank_ms = timed(f_rank, bkj, skj)
    return {
        "metric": "join_microbench",
        "n_build": n_build, "n_stream": n_stream, "reps": reps,
        "matches": m_ss,
        "pallas_probe_ms": round(pallas_ms, 2),
        "pallas_build_ms": round(pallas_build_ms, 2),
        "searchsorted_probe_ms": round(ss_ms, 2),
        "searchsorted_build_ms": round(ss_build_ms, 2),
        "laxsort_rank_ms": round(rank_ms, 2),
        "pallas_vs_laxsort": round(rank_ms / pallas_ms, 2),
        "parity_ok": pallas_ms <= rank_ms,
    }


def _latency_percentiles():
    """p50/p95/p99 end-to-end latency per priority class plus the admission
    queue-wait distribution, from the fixed-bucket histograms every
    completed action observes into (runtime/metrics.py; the serving STATS
    endpoint exposes the same families)."""
    from spark_rapids_tpu.runtime import metrics as M
    out = {}
    for name in sorted(M.histograms_snapshot()):
        if name.startswith("query.latency.priority"):
            key = "priority" + name[len("query.latency.priority"):]
        elif name == "admission.wait":
            key = "admission_wait"
        else:
            continue
        pct = M.histogram_percentiles(name)
        if pct is not None:
            out[key] = pct
    return out


def concurrent_bench(n: int, query: str = "q18", reps: int = 2,
                     endpoint: bool = False, replicas: int = 1):
    """Multi-tenant aggregate-throughput mode (``--concurrent N``): N copies
    of one TPC-H query run back-to-back (sequential) and then fanned out on
    N threads through the driver-side QueryScheduler (concurrent), value-
    checked and bit-identity-checked against each other. Prints one JSON
    line with the aggregate throughput ratio plus per-query isolation
    evidence: every query's SCOPED resilience counters (all zero with no
    faults — a peer's retries can no longer leak into another query's
    scope) and its distinct query id. On <2 cores the measurement still
    runs but the line carries ``gate_skipped`` so ci.sh can skip its
    >=1.2x assertion with the reason logged.

    ``--endpoint`` routes every submission through the Arrow-over-TCP
    serving endpoint (runtime/endpoint.py) instead of in-process collects:
    each worker is a real EndpointClient speaking SQL over a socket, the
    per-query isolation evidence comes from the wire's summary frame, and
    the line additionally embeds the process-wide resilience snapshot
    (ci.sh asserts it all-zero — serving through the front door with no
    faults must be invisible to every recovery ladder). Endpoint mode uses
    the official SQL text, so the query must be one of q1/q3/q5."""
    import threading
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from __graft_entry__ import _enable_compile_cache
    _enable_compile_cache()
    import spark_rapids_tpu  # noqa: F401  (enables x64)
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.session import TpuSession

    cores = os.cpu_count() or 1
    paths = tpch.generate(TPCH_SF, DATA_DIR)
    conf = {
        "spark.rapids.tpu.sql.format.parquet.reader.type": "COALESCING",
        "spark.rapids.tpu.pipeline.enabled": True,
        "spark.rapids.tpu.scheduler.maxConcurrent": n,
    }
    spark = TpuSession(conf)

    if endpoint:
        return _endpoint_concurrent_bench(spark, paths, n, query, reps, cores,
                                          replicas=replicas)

    def build_df():
        dfs = tpch.load(spark, paths, files_per_partition=4)
        return getattr(tpch, query)(dfs)

    warm = build_df()
    baseline = warm.collect().to_pylist()    # warm: compiles cached after

    # sequential: n runs back to back, per-rep median
    seq_ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(n):
            rows = build_df().collect().to_pylist()
            assert rows == baseline, "sequential run diverged"
        seq_ts.append(time.perf_counter() - t0)
    sequential_s = statistics.median(seq_ts)

    # concurrent: n threads, each its own DataFrame (own collector), one
    # barrier start; wall = slowest finisher
    def run_concurrent():
        results = [None] * n
        errors = []
        barrier = threading.Barrier(n + 1)

        def worker(i):
            df = build_df()
            try:
                barrier.wait()
                rows = df.collect().to_pylist()
                qm = df._last_collector
                results[i] = {
                    "query_id": qm.query_id,
                    "wall_s": round(qm.wall_s, 4),
                    "rows_ok": rows == baseline,
                    "resilience_nonzero": {
                        k: v for k, v in qm.query_resilience().items() if v},
                }
            except BaseException as e:  # noqa: BLE001
                errors.append(repr(e)[:200])

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(n)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        return time.perf_counter() - t0, results, errors

    conc_ts, results, errors = [], None, None
    for _ in range(reps):
        wall, results, errors = run_concurrent()
        if errors:
            break
        conc_ts.append(wall)
    concurrent_s = statistics.median(conc_ts) if conc_ts else 0.0

    line = {
        "metric": f"tpch_sf{TPCH_SF}_{query}_concurrent{n}",
        "n": n, "query": query, "reps": reps, "cores": cores,
        "sequential_s": round(sequential_s, 4),
        "concurrent_s": round(concurrent_s, 4),
        "throughput_x": (round(sequential_s / concurrent_s, 3)
                         if concurrent_s else 0.0),
        "per_query": results,
        "isolation_ok": bool(results) and all(
            r and r["rows_ok"] and not r["resilience_nonzero"]
            and len({x["query_id"] for x in results}) == n
            for r in results),
        # per-priority latency distribution across every run this process
        # made (sequential + concurrent): the serving tier's SLO numbers
        "latency": _latency_percentiles(),
    }
    if errors:
        line["errors"] = errors
    if cores < 2:
        line["gate_skipped"] = (
            f"{cores} core(s): concurrent queries cannot overlap on one "
            "core; throughput gate needs >=2")
    return line


def _endpoint_concurrent_bench(spark, paths, n, query, reps, cores,
                               replicas=1):
    """The --endpoint half of concurrent_bench: n clients over TCP."""
    import threading
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.runtime import metrics as M
    from spark_rapids_tpu.runtime.endpoint import EndpointClient
    from spark_rapids_tpu.sql.tpch_queries import SQL_QUERIES

    assert query in SQL_QUERIES, \
        f"--endpoint needs official SQL text; {query} not in {sorted(SQL_QUERIES)}"
    sql = SQL_QUERIES[query]
    tpch.load(spark, paths, files_per_partition=4)   # registers temp views
    baseline = spark.sql(sql).collect().to_pylist()  # warm + value oracle
    if replicas > 1:
        return _fleet_concurrent_bench(baseline, sql, n, query, reps, cores,
                                       replicas)
    ep = spark.serve()
    addr = ("127.0.0.1", ep.port)
    try:
        # sequential: n wire submissions back to back, per-rep median
        seq_ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(n):
                cli = EndpointClient(addr, timeout_s=300)
                rows = cli.submit(sql).to_pylist()
                assert rows == baseline, "sequential endpoint run diverged"
            seq_ts.append(time.perf_counter() - t0)
        sequential_s = statistics.median(seq_ts)

        def run_concurrent():
            results = [None] * n
            errors = []
            barrier = threading.Barrier(n + 1)

            def worker(i):
                cli = EndpointClient(addr, timeout_s=300)
                try:
                    barrier.wait()
                    rows = cli.submit(sql).to_pylist()
                    s = cli.last_summary or {}
                    results[i] = {
                        "query_id": s.get("query"),
                        "wall_s": s.get("wall_s"),
                        "rows_ok": rows == baseline,
                        "resilience_nonzero": s.get("resilience") or {},
                    }
                except BaseException as e:  # noqa: BLE001
                    errors.append(repr(e)[:200])

            threads = [threading.Thread(target=worker, args=(i,),
                                        daemon=True) for i in range(n)]
            for t in threads:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            return time.perf_counter() - t0, results, errors

        conc_ts, results, errors = [], None, None
        for _ in range(reps):
            wall, results, errors = run_concurrent()
            if errors:
                break
            conc_ts.append(wall)
        concurrent_s = statistics.median(conc_ts) if conc_ts else 0.0
    finally:
        ep.shutdown(grace_s=5)

    line = {
        "metric": f"tpch_sf{TPCH_SF}_{query}_endpoint_concurrent{n}",
        "n": n, "query": query, "reps": reps, "cores": cores,
        "endpoint": True,
        "sequential_s": round(sequential_s, 4),
        "concurrent_s": round(concurrent_s, 4),
        "throughput_x": (round(sequential_s / concurrent_s, 3)
                         if concurrent_s else 0.0),
        "per_query": results,
        "isolation_ok": bool(results) and all(
            r and r["rows_ok"] and not r["resilience_nonzero"]
            and len({x["query_id"] for x in results}) == n
            for r in results),
        # serving with no faults must be invisible to every recovery
        # ladder — including the endpoint's own disconnect counter
        "resilience": M.resilience_snapshot(),
        "latency": _latency_percentiles(),
    }
    if errors:
        line["errors"] = errors
    if cores < 2:
        line["gate_skipped"] = (
            f"{cores} core(s): concurrent queries cannot overlap on one "
            "core; throughput gate needs >=2")
    return line


def _fleet_concurrent_bench(baseline, sql, n, query, reps, cores, replicas):
    """The --replicas R half of endpoint mode: R real replica PROCESSES
    (tools/fleet_replica.py) registered in one fleet directory and sharing
    one compiled-stage cache — replica 0 compiles the workload, the rest
    replay its shapes warm. Sequential = n wire submissions through ONE
    replica; concurrent = n clients fanned across the fleet, worker i
    leading with replica i %% R and carrying the rest as its failover
    chain. The line embeds the client-side resilience snapshot (with no
    faults, spreading load across replicas must count ZERO failovers) plus
    the serving-latency trajectory: per-replica journey counts
    (served/failover/cached) and client-observed fleet p50/p95/p99."""
    import signal
    import threading
    from spark_rapids_tpu.runtime import metrics as M
    from spark_rapids_tpu.runtime.endpoint import EndpointClient

    work = f"/tmp/srt_fleet_bench_{os.getpid()}"
    fleet_dir = os.path.join(work, "fleet")
    cache_dir = os.path.join(work, "stage_cache")
    for d in (fleet_dir, cache_dir):
        os.makedirs(d, exist_ok=True)
    repl_script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "tools", "fleet_replica.py")
    procs, addrs = [], []
    try:
        for r in range(replicas):
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            proc = subprocess.Popen(
                [sys.executable, repl_script,
                 "--fleet-dir", fleet_dir,
                 "--data-dir", DATA_DIR, "--sf", str(TPCH_SF),
                 "--stage-cache-dir", cache_dir,
                 # generous lease: a GIL stall during a compile burst must
                 # not expire a LIVE replica mid-benchmark
                 "--lease-timeout", "10", "--heartbeat", "1",
                 "--max-concurrent", str(n)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env)
            port = None
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                ln = proc.stdout.readline()
                if ln.startswith("READY "):
                    port = int(ln.split()[1])
                    break
                if proc.poll() is not None:
                    break
            assert port is not None, f"fleet replica {r} never became READY"
            threading.Thread(target=proc.stdout.read, daemon=True).start()
            procs.append(proc)
            addrs.append(("127.0.0.1", port))

        # warm each replica once; replica 0 compiles into the shared stage
        # cache first, so the rest start from its compiled shapes
        for a in addrs:
            rows = EndpointClient(a, timeout_s=600).submit(sql).to_pylist()
            assert rows == baseline, "fleet replica warm-up diverged"

        # sequential: n wire submissions back to back through one replica
        seq_ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(n):
                rows = EndpointClient(
                    addrs[0], timeout_s=600).submit(sql).to_pylist()
                assert rows == baseline, "sequential fleet run diverged"
            seq_ts.append(time.perf_counter() - t0)
        sequential_s = statistics.median(seq_ts)

        def run_concurrent():
            results = [None] * n
            errors = []
            barrier = threading.Barrier(n + 1)

            def worker(i):
                order = addrs[i % replicas:] + addrs[:i % replicas]
                cli = EndpointClient(order, timeout_s=600)
                retries = []
                try:
                    barrier.wait()
                    t0 = time.perf_counter()
                    rows = cli.submit_with_retry(
                        sql,
                        on_retry=lambda a, d: retries.append(a)).to_pylist()
                    client_s = time.perf_counter() - t0
                    s = cli.last_summary or {}
                    results[i] = {
                        "query_id": s.get("query"),
                        # the SERVING replica's identity from the summary
                        # frame (the journey plane stamps it), so failovers
                        # attribute the serve to where it actually landed
                        "replica": s.get("replica")
                        or f"{cli.address[0]}:{cli.address[1]}",
                        "journey": cli.last_journey,
                        "failovers": len(retries),
                        "cached": bool(s.get("cached")),
                        "wall_s": s.get("wall_s"),
                        "client_s": round(client_s, 4),
                        "rows_ok": rows == baseline,
                        "resilience_nonzero": s.get("resilience") or {},
                    }
                except BaseException as e:  # noqa: BLE001
                    errors.append(repr(e)[:200])

            threads = [threading.Thread(target=worker, args=(i,),
                                        daemon=True) for i in range(n)]
            for t in threads:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            return time.perf_counter() - t0, results, errors

        conc_ts, results, errors, all_results = [], None, None, []
        for _ in range(reps):
            wall, results, errors = run_concurrent()
            if errors:
                break
            conc_ts.append(wall)
            all_results.extend(r for r in results if r)
        concurrent_s = statistics.median(conc_ts) if conc_ts else 0.0

        # per-replica journey counts across every rep: where each serve
        # landed, how many arrived via failover, how many were cache hits
        journeys = {}
        for r in all_results:
            d = journeys.setdefault(
                r["replica"], {"served": 0, "failover": 0, "cached": 0})
            d["cached" if r["cached"] else "served"] += 1
            d["failover"] += r["failovers"]
        lats = sorted(r["client_s"] for r in all_results
                      if r.get("client_s") is not None)

        def _pct(p):
            return (round(lats[min(len(lats) - 1,
                                   int(p / 100.0 * len(lats)))], 4)
                    if lats else None)

        fleet_latency = {"p50": _pct(50), "p95": _pct(95), "p99": _pct(99)}
    finally:
        for proc in procs:
            try:
                proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        for proc in procs:
            try:
                proc.wait(timeout=90)
            except Exception:   # noqa: BLE001
                proc.kill()

    line = {
        "metric": f"tpch_sf{TPCH_SF}_{query}_endpoint{replicas}r_concurrent{n}",
        "n": n, "query": query, "reps": reps, "cores": cores,
        "endpoint": True, "replicas": replicas,
        "sequential_s": round(sequential_s, 4),
        "concurrent_s": round(concurrent_s, 4),
        "throughput_x": (round(sequential_s / concurrent_s, 3)
                         if concurrent_s else 0.0),
        "per_query": results,
        "isolation_ok": bool(results) and all(
            r and r["rows_ok"] and not r["resilience_nonzero"]
            and len({x["query_id"] for x in results}) == n
            for r in results),
        # CLIENT-side registry: a no-faults fleet run must count zero
        # replicaFailovers — load spreading is routing, not recovery
        "resilience": M.resilience_snapshot(),
        "latency": _latency_percentiles(),
        # serving-latency trajectory: per-replica journey outcome counts +
        # client-observed (submit -> last row) percentiles across every
        # rep — bench_compare.py diffs these between runs
        "journeys": journeys,
        "fleet_latency": fleet_latency,
    }
    if errors:
        line["errors"] = errors
    if cores < 2:
        line["gate_skipped"] = (
            f"{cores} core(s): replicas cannot overlap on one core; "
            "fleet throughput gate needs >=2")
    return line


def _spawn(extra_env, timeout_s):
    """Run this script as a measuring child; return its last JSON line or None."""
    env = dict(os.environ)
    env.update(extra_env)
    env["_SRT_BENCH_CHILD"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=timeout_s)
    except subprocess.TimeoutExpired as e:
        out = e.stdout
        if isinstance(out, bytes):
            out = out.decode("utf-8", "replace")
        return None, f"timeout after {timeout_s}s: {(out or '')[-2000:]}"
    tail = (proc.stdout or "")[-2000:]
    for ln in reversed((proc.stdout or "").splitlines()):
        ln = ln.strip()
        if ln.startswith("{") and ln.endswith("}"):
            try:
                parsed = json.loads(ln)
                if "metric" in parsed:
                    return parsed, tail
            except (ValueError, TypeError):
                continue
    return None, f"rc={proc.returncode}: {tail}"


def _probe_backend():
    """Is the accelerator backend usable at all? Short subprocess probe — a
    wedged tunnel hangs even trivial ops, so never dispatch without this."""
    code = ("import jax; d = jax.devices(); "
            "import jax.numpy as jnp; "
            "x = jnp.ones((8,)) + 1; x.block_until_ready(); "
            "import numpy as np; print('PROBE_OK', float(np.asarray(x).sum()),"
            " d[0].platform)")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=dict(os.environ),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=PROBE_TIMEOUT_S)
        return proc.returncode == 0 and "PROBE_OK" in (proc.stdout or "")
    except subprocess.TimeoutExpired:
        return False


def parent_main():
    """Never exits non-zero; always prints one JSON line."""
    attempts = []
    # ladder: full SF with the long budget, then a smaller SF with a tighter
    # budget (fewer rows AND fewer fresh compiles) — a degraded-scale TPU
    # number beats a CPU fallback
    ladder = [({}, CHILD_TIMEOUT_S),
              ({"TPCH_SF": "0.01", "TPCH_DIR": "/tmp/tpch_sf0.01"}, 1200)]
    for attempt, (env, budget) in enumerate(ladder):
        if _probe_backend():
            parsed, err = _spawn(env, budget)
            if parsed is not None:
                if env.get("TPCH_SF"):
                    parsed["degraded"] = (parsed.get("degraded", "") +
                                          " reduced-sf=" + env["TPCH_SF"]).strip()
                print(json.dumps(parsed))
                return
            attempts.append(f"accel attempt {attempt}: {err}")
        else:
            attempts.append(f"accel probe {attempt}: backend unavailable")
        if attempt == 0:
            time.sleep(10)
    # degraded path: force CPU so the metric is never null
    parsed, err = _spawn({"JAX_PLATFORMS": "cpu"}, CHILD_TIMEOUT_S)
    if parsed is not None:
        parsed["degraded"] = "cpu-fallback: " + "; ".join(attempts)[-500:]
        print(json.dumps(parsed))
        return
    attempts.append(f"cpu fallback: {err}")
    print(json.dumps({
        "metric": f"tpch_sf{TPCH_SF}_q1q3q5_geomean",
        "value": 0.0,
        "unit": "Mrows/s",
        "vs_baseline": 0.0,
        "degraded": "; ".join(attempts)[-900:],
    }))


if __name__ == "__main__":
    if "--join-micro" in sys.argv:
        # standalone kernel microbench (ci.sh smoke gate): one JSON line
        with watcher_paused():
            print(json.dumps(join_microbench(smoke="--smoke" in sys.argv)))
    elif "--concurrent" in sys.argv:
        # multi-tenant aggregate-throughput mode: one JSON line;
        # --endpoint routes every submission over the Arrow-over-TCP
        # serving endpoint (SQL text, so q1/q3/q5 only)
        i = sys.argv.index("--concurrent")
        n = int(sys.argv[i + 1]) if len(sys.argv) > i + 1 else 4
        ep_mode = "--endpoint" in sys.argv
        q = (sys.argv[sys.argv.index("--query") + 1]
             if "--query" in sys.argv else ("q5" if ep_mode else "q18"))
        # --replicas R (endpoint mode only): R real replica processes
        # behind one fleet directory + shared stage cache
        r = (int(sys.argv[sys.argv.index("--replicas") + 1])
             if "--replicas" in sys.argv else 1)
        with watcher_paused():
            print(json.dumps(concurrent_bench(n, q, endpoint=ep_mode,
                                              replicas=r)))
    elif os.environ.get("_SRT_BENCH_CHILD") == "1":
        child_main()
    else:
        parent_main()
