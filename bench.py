"""Benchmark: TPC-H q1/q3/q5 end-to-end through the session API.

BASELINE.md config-2 (TPC-H SF0.1+ scan+filter+agg+join on one TPU VM),
replacing round-1's synthetic fused stage. Each query runs end-to-end
(parquet scan → device pipeline → collect) and is first CHECKED against an
independent single-core NumPy oracle (benchmarks/tpch.py) — a wrong answer
reports value 0 rather than a throughput. Prints ONE JSON line:

  value       = geomean over q1/q3/q5 of (lineitem rows / hot-run seconds), Mrows/s
  vs_baseline = geomean over queries of (numpy oracle E2E time / hot-run time),
                where the oracle re-reads the query's parquet tables per run —
                both sides pay the scan (VERDICT r4 next #2: the old preloaded-
                array oracle capped q3/q5 at the decode floor). The reference's
                own claim is 3x-7x vs CPU Spark, docs/FAQ.md:82-88.
  vs_baseline_compute = the round-4-and-earlier denominator (oracle computes on
                preloaded arrays; engine still pays its scan), kept one round
                for continuity.

Resilience (round-1 postmortem + round-2 tunnel-wedge postmortem): the
measurement runs in a CHILD process with a timeout; the parent probes the
backend first with a SHORT timeout (a wedged tunnel hangs even trivial adds —
see .claude/skills/verify/SKILL.md), retries once, falls back to the CPU
platform if the accelerator never comes up, and ALWAYS prints exactly one
JSON line and exits 0.
"""

import json
import math
import os
import subprocess
import sys
import time

TPCH_SF = float(os.environ.get("TPCH_SF", "0.1"))
DATA_DIR = os.environ.get("TPCH_DIR", f"/tmp/tpch_sf{TPCH_SF}")
CHILD_TIMEOUT_S = 2400
PROBE_TIMEOUT_S = 240   # first TPU compile/init can take ~40s; be generous


def _check_q1(got, exp):
    assert len(got) == len(exp), (len(got), len(exp))
    for g_, e in zip(got, exp):
        g = list(g_.values())
        assert g[0] == e[0] and g[1] == e[1], (g, e)
        for a, b in zip(g[2:], e[2:]):
            assert abs(a - b) <= 1e-6 * max(1.0, abs(b)), (g, e)


def _check_q3(got, exp):
    assert len(got) == len(exp), (len(got), len(exp))
    for g, (k, d, p, rev) in zip(got, exp):
        assert g["l_orderkey"] == k, (g, k)
        assert abs(g["revenue"] - rev) <= 1e-6 * max(1.0, abs(rev))


def _check_q5(got, exp):
    assert len(got) == len(exp), (len(got), len(exp))
    for g, (n, v) in zip(got, exp):
        assert g["n_name"] == n, (g, n)
        assert abs(g["revenue"] - v) <= 1e-6 * max(1.0, abs(v))


CHECKS = {"q1": _check_q1, "q3": _check_q3, "q5": _check_q5}
NP_QUERIES = {"q1": "np_q1", "q3": "np_q3", "q5": "np_q5"}
# (table -> columns) each query scans — the fair oracle re-reads exactly
# these per run, mirroring what the engine's COLUMN-PRUNED plan scans every
# collect() (plan/pruning.py narrows the FileScanNode the same way)
Q_TABLES = {
    "q1": {"lineitem": ["l_discount", "l_extendedprice", "l_linestatus",
                        "l_quantity", "l_returnflag", "l_shipdate", "l_tax"]},
    "q3": {"customer": ["c_custkey", "c_mktsegment"],
           "orders": ["o_custkey", "o_orderdate", "o_orderkey",
                      "o_shippriority"],
           "lineitem": ["l_discount", "l_extendedprice", "l_orderkey",
                        "l_shipdate"]},
    "q5": {"customer": ["c_custkey", "c_nationkey"],
           "orders": ["o_custkey", "o_orderdate", "o_orderkey"],
           "lineitem": ["l_discount", "l_extendedprice", "l_orderkey",
                        "l_suppkey"],
           "supplier": ["s_nationkey", "s_suppkey"],
           "nation": ["n_name", "n_nationkey", "n_regionkey"],
           "region": ["r_name", "r_regionkey"]},
}


def child_main():
    """Measured run; prints the JSON line on success. Runs in a subprocess so a
    wedged tunnel or backend crash cannot take down the parent."""
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the axon site hook re-selects TPU regardless of env; override it
        jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: repeated bench runs (and the driver's
    # end-of-round run) must not re-pay every remote TPU compile
    from __graft_entry__ import _enable_compile_cache
    _enable_compile_cache()
    import spark_rapids_tpu  # noqa: F401  (enables x64)
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.session import TpuSession

    platform = jax.devices()[0].platform
    paths = tpch.generate(TPCH_SF, DATA_DIR)
    # COALESCING stitches the per-partition files into few large batches —
    # fewer per-batch fixed costs; measured fastest on both backends at this
    # scale (docs/tuning.md; reference COALESCING reader role)
    spark = TpuSession({
        "spark.rapids.tpu.sql.format.parquet.reader.type": "COALESCING"})
    dfs = tpch.load(spark, paths, files_per_partition=4)
    tb = tpch.load_np(paths)
    n_lineitem = len(tb["lineitem"]["l_orderkey"])

    from spark_rapids_tpu.benchmarks.common import read_np

    speedups_e2e, speedups_compute, mrows = [], [], []
    for name, q in tpch.QUERIES.items():
        df = q(dfs)
        got = df.collect().to_pylist()          # warm (compiles cached after)
        exp = getattr(tpch, NP_QUERIES[name])(tb)
        CHECKS[name](got, exp)                  # wrong answer → no number
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            df.collect()
            best = min(best, time.perf_counter() - t0)
        # fair oracle: re-read this query's tables from parquet + compute
        # (both sides pay the scan; OS page cache is warm for both)
        t0 = time.perf_counter()
        tb_q = {t: read_np(paths[t], columns=cols)
                for t, cols in Q_TABLES[name].items()}
        getattr(tpch, NP_QUERIES[name])(tb_q)
        np_e2e = time.perf_counter() - t0
        del tb_q
        # legacy denominator: oracle computes on preloaded arrays
        t0 = time.perf_counter()
        getattr(tpch, NP_QUERIES[name])(tb)
        np_compute = time.perf_counter() - t0
        speedups_e2e.append(np_e2e / best)
        speedups_compute.append(np_compute / best)
        mrows.append(n_lineitem / best / 1e6)

    geo = lambda xs: math.exp(sum(math.log(x) for x in xs) / len(xs))
    line = {
        "metric": f"tpch_sf{TPCH_SF}_q1q3q5_geomean",
        "value": round(geo(mrows), 3),
        "unit": "Mrows/s",
        "vs_baseline": round(geo(speedups_e2e), 3),
        "vs_baseline_compute": round(geo(speedups_compute), 3),
        "baseline_denominator": "numpy-oracle e2e (per-query parquet re-read)",
    }
    if platform != "tpu":
        line["degraded"] = f"platform={platform}"
    # secondary metric: the 22-query TPC-DS sweep at small scale (breadth —
    # window/decimal/basket shapes; reference qa_nightly role). Failures
    # never take down the primary metric. Default OFF on the real chip: the
    # sweep's compile volume could eat the child budget, and a timeout kill
    # mid-dispatch wedges the tunnel (docs/perf_notes.md).
    default_secondary = "1" if platform != "tpu" else "0"
    if os.environ.get("TPCDS_SECONDARY", default_secondary) == "1":
        # shared setup: a failure here is reported as THE error for both
        # sweeps (not a downstream NameError masking the real cause)
        try:
            from spark_rapids_tpu.benchmarks import tpcds
            sf = float(os.environ.get("TPCDS_SF", "0.01"))
            dpaths = tpcds.generate(sf, os.environ.get(
                "TPCDS_DIR", f"/tmp/tpcds_sf{sf}"))
            ddfs = tpcds.load(spark, dpaths)
            dtb = tpcds.load_np(dpaths)
        except Exception as e:  # noqa: BLE001
            line["secondary"] = {"error": repr(e)[:200]}
            line["sql_suite"] = {"error": repr(e)[:200]}
            print(json.dumps(line))
            return
        try:
            # wall_s times ENGINE execution only (plan + collect); the
            # oracle evaluation and value check run off the clock
            wall = 0.0
            results = []
            for qname, q in tpcds.QUERIES.items():
                t0 = time.perf_counter()
                got = [tuple(r.values())
                       for r in q(ddfs).collect().to_pylist()]
                wall += time.perf_counter() - t0
                results.append((qname, got))
            n_ok, failed = 0, []
            for qname, got in results:
                exp = [tuple(r) for r in tpcds.NP_QUERIES[qname](dtb)]
                try:
                    # full value equality (exact + per-column float approx),
                    # same check as tests/test_tpcds.py
                    tpcds.check_rows(got, exp, tpcds.FLOAT_COLS[qname])
                    n_ok += 1
                except Exception:  # noqa: BLE001 — one bad query must not
                    failed.append(qname)  # void the other 21 results
            line["secondary"] = {
                "metric": f"tpcds_sf{sf}_22q_sweep",
                "queries_ok": n_ok, "queries_total": len(tpcds.QUERIES),
                "check": "value-equality",
                "wall_s": round(wall, 2),
            }
            if failed:
                line["secondary"]["failed"] = failed
        except Exception as e:  # noqa: BLE001 — secondary must not kill primary
            line["secondary"] = {"error": repr(e)[:200]}
        try:
            # the official-SQL-text suite through session.sql() — the
            # reference's qa_nightly_sql.py role, value-checked. Every
            # query runs under its own try: one engine error records that
            # query as failed without voiding the rest (or the DataFrame
            # sweep above, which has its own handler).
            from spark_rapids_tpu.sql.tpcds_queries import SQL_QUERIES
            oracles = tpcds.sql_suite_oracles()
            wall = 0.0
            results = []
            n_ok, failed = 0, []
            for qname in sorted(SQL_QUERIES, key=lambda q: int(q[1:])):
                try:
                    t0 = time.perf_counter()
                    got = [tuple(r.values())
                           for r in spark.sql(SQL_QUERIES[qname])
                           .collect().to_pylist()]
                    wall += time.perf_counter() - t0
                    results.append((qname, got))
                except Exception:  # noqa: BLE001
                    failed.append(qname)
            for qname, got in results:       # checks run off the clock
                oracle, float_cols = oracles[qname]
                try:
                    tpcds.check_rows(got, [tuple(r) for r in oracle(dtb)],
                                     float_cols)
                    n_ok += 1
                except Exception:  # noqa: BLE001
                    failed.append(qname)
            line["sql_suite"] = {
                "metric": f"tpcds_sf{sf}_{len(SQL_QUERIES)}q_sql_sweep",
                "queries_ok": n_ok, "queries_total": len(SQL_QUERIES),
                "check": "value-equality",
                "wall_s": round(wall, 2),
            }
            if failed:
                line["sql_suite"]["failed"] = failed
        except Exception as e:  # noqa: BLE001
            line["sql_suite"] = {"error": repr(e)[:200]}
    print(json.dumps(line))


def _spawn(extra_env, timeout_s):
    """Run this script as a measuring child; return its last JSON line or None."""
    env = dict(os.environ)
    env.update(extra_env)
    env["_SRT_BENCH_CHILD"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=timeout_s)
    except subprocess.TimeoutExpired as e:
        out = e.stdout
        if isinstance(out, bytes):
            out = out.decode("utf-8", "replace")
        return None, f"timeout after {timeout_s}s: {(out or '')[-2000:]}"
    tail = (proc.stdout or "")[-2000:]
    for ln in reversed((proc.stdout or "").splitlines()):
        ln = ln.strip()
        if ln.startswith("{") and ln.endswith("}"):
            try:
                parsed = json.loads(ln)
                if "metric" in parsed:
                    return parsed, tail
            except (ValueError, TypeError):
                continue
    return None, f"rc={proc.returncode}: {tail}"


def _probe_backend():
    """Is the accelerator backend usable at all? Short subprocess probe — a
    wedged tunnel hangs even trivial ops, so never dispatch without this."""
    code = ("import jax; d = jax.devices(); "
            "import jax.numpy as jnp; "
            "x = jnp.ones((8,)) + 1; x.block_until_ready(); "
            "import numpy as np; print('PROBE_OK', float(np.asarray(x).sum()),"
            " d[0].platform)")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=dict(os.environ),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=PROBE_TIMEOUT_S)
        return proc.returncode == 0 and "PROBE_OK" in (proc.stdout or "")
    except subprocess.TimeoutExpired:
        return False


def parent_main():
    """Never exits non-zero; always prints one JSON line."""
    attempts = []
    # ladder: full SF with the long budget, then a smaller SF with a tighter
    # budget (fewer rows AND fewer fresh compiles) — a degraded-scale TPU
    # number beats a CPU fallback
    ladder = [({}, CHILD_TIMEOUT_S),
              ({"TPCH_SF": "0.01", "TPCH_DIR": "/tmp/tpch_sf0.01"}, 1200)]
    for attempt, (env, budget) in enumerate(ladder):
        if _probe_backend():
            parsed, err = _spawn(env, budget)
            if parsed is not None:
                if env.get("TPCH_SF"):
                    parsed["degraded"] = (parsed.get("degraded", "") +
                                          " reduced-sf=" + env["TPCH_SF"]).strip()
                print(json.dumps(parsed))
                return
            attempts.append(f"accel attempt {attempt}: {err}")
        else:
            attempts.append(f"accel probe {attempt}: backend unavailable")
        if attempt == 0:
            time.sleep(10)
    # degraded path: force CPU so the metric is never null
    parsed, err = _spawn({"JAX_PLATFORMS": "cpu"}, CHILD_TIMEOUT_S)
    if parsed is not None:
        parsed["degraded"] = "cpu-fallback: " + "; ".join(attempts)[-500:]
        print(json.dumps(parsed))
        return
    attempts.append(f"cpu fallback: {err}")
    print(json.dumps({
        "metric": f"tpch_sf{TPCH_SF}_q1q3q5_geomean",
        "value": 0.0,
        "unit": "Mrows/s",
        "vs_baseline": 0.0,
        "degraded": "; ".join(attempts)[-900:],
    }))


if __name__ == "__main__":
    if os.environ.get("_SRT_BENCH_CHILD") == "1":
        child_main()
    else:
        parent_main()
