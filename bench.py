"""Benchmark: fused scan→filter→project→hash-aggregate stage throughput.

BASELINE.md config-1 analog (q5-like hash aggregate): one XLA program doing
filter + project + group-by(sum/count/min/max) over a padded columnar batch —
the TPU-native counterpart of the reference's GpuFilterExec → GpuProjectExec →
GpuHashAggregateExec pipeline (SURVEY.md §3.3). Prints ONE JSON line.

`vs_baseline` is speedup over a single-core NumPy columnar implementation of the
same query on the same host (the reference's own published claim is 3x-7x vs CPU
Spark, docs/FAQ.md:82-88 — no numeric tables exist in-tree, BASELINE.md).
"""

import json
import time

import numpy as np


CAP = 1 << 22          # 4M row padded batch
N_ROWS = (1 << 22) - 37
N_KEYS = 4096
ITERS = 10


def host_baseline(key_vals, key_valid, val_vals, val_valid, n):
    """Single-core NumPy version of the same query (CPU Spark stand-in)."""
    k = key_vals[:n]
    kv = key_valid[:n]
    v = val_vals[:n]
    vm = val_valid[:n]
    keep = vm & (v > 0.0)
    k, kv, v = k[keep], kv[keep], v[keep]
    proj = v * 2.0 + k.astype(np.float64) * 0.5
    pvalid = kv  # val is valid for all kept rows
    # group by (key, key_valid): null keys form one group
    gk = np.where(kv, k, np.int64(-(1 << 62)))
    order = np.argsort(gk, kind="stable")
    gk, proj, pvalid = gk[order], proj[order], pvalid[order]
    uniq, start = np.unique(gk, return_index=True)
    sums = np.add.reduceat(np.where(pvalid, proj, 0.0), start)
    cnts = np.add.reduceat(pvalid.astype(np.int64), start)
    mins = np.minimum.reduceat(np.where(pvalid, proj, np.inf), start)
    maxs = np.maximum.reduceat(np.where(pvalid, proj, -np.inf), start)
    return uniq, sums, cnts, mins, maxs


def timed_loop_fn(stage, iters):
    """Run the stage `iters` times on-device inside one dispatch, with a data
    dependency between iterations so XLA cannot elide or overlap them. One
    dispatch per measurement is essential: the device link has O(10ms) roundtrip
    latency, so per-call host timing measures the tunnel, not the kernel."""
    import jax
    import jax.numpy as jnp

    def body(_, carry):
        kv, km, vv, vm, nr = carry
        out = stage(kv, km, vv, vm, nr)
        # fold a result element back into the input (value ~0, keeps dtypes)
        delta = (out[1][0] * 1e-30).astype(vv.dtype)
        return (kv, km, vv + delta, vm, nr)

    def run(kv, km, vv, vm, nr):
        carry = jax.lax.fori_loop(0, iters, body, (kv, km, vv, vm, nr))
        return stage(*carry)

    return jax.jit(run)


def main():
    import jax
    from __graft_entry__ import _build_stage

    rng = np.random.default_rng(42)
    key_vals = rng.integers(0, N_KEYS, CAP).astype(np.int64)
    key_valid = rng.random(CAP) > 0.02
    val_vals = rng.normal(0, 10, CAP)
    val_valid = rng.random(CAP) > 0.02
    num_rows = np.int32(N_ROWS)

    stage = _build_stage()
    dev_args = [jax.device_put(a) for a in
                (key_vals, key_valid, val_vals, val_valid)]

    def measure(iters):
        fn = timed_loop_fn(stage, iters)
        out = fn(*dev_args, num_rows)               # compile + warmup
        _ = np.asarray(out[-1])                     # full host sync
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(*dev_args, num_rows)
            _ = np.asarray(out[-1])
            best = min(best, time.perf_counter() - t0)
        return best, out

    t_short, _ = measure(1)
    t_long, out = measure(1 + ITERS)
    tpu_s = max((t_long - t_short) / ITERS, 1e-9)

    t0 = time.perf_counter()
    ref = host_baseline(key_vals, key_valid, val_vals, val_valid, N_ROWS)
    cpu_s = time.perf_counter() - t0

    # correctness spot-check: group count and total sum match the host baseline
    n_groups = int(out[-1])
    assert n_groups == len(ref[0]), (n_groups, len(ref[0]))
    dev_sum = float(np.asarray(out[1])[:n_groups].sum())
    assert abs(dev_sum - float(ref[1].sum())) < 1e-6 * max(1.0, abs(dev_sum))

    rows_per_s = N_ROWS / tpu_s
    print(json.dumps({
        "metric": "fused_hash_aggregate_throughput",
        "value": round(rows_per_s / 1e6, 3),
        "unit": "Mrows/s",
        "vs_baseline": round(cpu_s / tpu_s, 3),
    }))


if __name__ == "__main__":
    main()
