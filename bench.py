"""Benchmark: fused scan→filter→project→hash-aggregate stage throughput.

BASELINE.md config-1 analog (q5-like hash aggregate): one XLA program doing
filter + project + group-by(sum/count/min/max) over a padded columnar batch —
the TPU-native counterpart of the reference's GpuFilterExec → GpuProjectExec →
GpuHashAggregateExec pipeline (SURVEY.md §3.3). Prints ONE JSON line.

`vs_baseline` is speedup over a single-core NumPy columnar implementation of the
same query on the same host (the reference's own published claim is 3x-7x vs CPU
Spark, docs/FAQ.md:82-88 — no numeric tables exist in-tree, BASELINE.md).

Resilience (round-1 postmortem: a single axon backend-init failure produced
rc=1 and a null metric): the measurement runs in a CHILD process with a
timeout; the parent probes the backend first, retries once on failure, falls
back to the CPU platform if the accelerator never comes up, and ALWAYS prints
exactly one JSON line and exits 0.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np


CAP = 1 << 22          # 4M row padded batch
N_ROWS = (1 << 22) - 37
N_KEYS = 4096
ITERS = 10

CHILD_TIMEOUT_S = 1200
PROBE_TIMEOUT_S = 240   # first TPU compile/init can take ~40s; be generous


def host_baseline(key_vals, key_valid, val_vals, val_valid, n):
    """Single-core NumPy version of the same query (CPU Spark stand-in)."""
    k = key_vals[:n]
    kv = key_valid[:n]
    v = val_vals[:n]
    vm = val_valid[:n]
    keep = vm & (v > 0.0)
    k, kv, v = k[keep], kv[keep], v[keep]
    proj = v * 2.0 + k.astype(np.float64) * 0.5
    pvalid = kv  # val is valid for all kept rows
    # group by (key, key_valid): null keys form one group
    gk = np.where(kv, k, np.int64(-(1 << 62)))
    order = np.argsort(gk, kind="stable")
    gk, proj, pvalid = gk[order], proj[order], pvalid[order]
    uniq, start = np.unique(gk, return_index=True)
    sums = np.add.reduceat(np.where(pvalid, proj, 0.0), start)
    cnts = np.add.reduceat(pvalid.astype(np.int64), start)
    mins = np.minimum.reduceat(np.where(pvalid, proj, np.inf), start)
    maxs = np.maximum.reduceat(np.where(pvalid, proj, -np.inf), start)
    return uniq, sums, cnts, mins, maxs


def timed_loop_fn(stage, iters):
    """Run the stage `iters` times on-device inside one dispatch, with a data
    dependency between iterations so XLA cannot elide or overlap them. One
    dispatch per measurement is essential: the device link has O(10ms) roundtrip
    latency, so per-call host timing measures the tunnel, not the kernel."""
    import jax

    def body(_, carry):
        kv, km, vv, vm, nr = carry
        out = stage(kv, km, vv, vm, nr)
        # fold a result element back into the input (value ~0, keeps dtypes)
        delta = (out[1][0] * 1e-30).astype(vv.dtype)
        return (kv, km, vv + delta, vm, nr)

    def run(kv, km, vv, vm, nr):
        carry = jax.lax.fori_loop(0, iters, body, (kv, km, vv, vm, nr))
        return stage(*carry)

    return jax.jit(run)


def child_main():
    """Measured run; prints the JSON line on success. Runs in a subprocess so a
    wedged tunnel or backend crash cannot take down the parent."""
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the axon site hook re-selects TPU regardless of env; override it
        jax.config.update("jax_platforms", "cpu")
    from __graft_entry__ import _build_stage

    platform = jax.devices()[0].platform

    rng = np.random.default_rng(42)
    key_vals = rng.integers(0, N_KEYS, CAP).astype(np.int64)
    key_valid = rng.random(CAP) > 0.02
    val_vals = rng.normal(0, 10, CAP)
    val_valid = rng.random(CAP) > 0.02
    num_rows = np.int32(N_ROWS)

    stage = _build_stage()
    dev_args = [jax.device_put(a) for a in
                (key_vals, key_valid, val_vals, val_valid)]

    def measure(iters):
        fn = timed_loop_fn(stage, iters)
        out = fn(*dev_args, num_rows)               # compile + warmup
        _ = np.asarray(out[-1])                     # full host sync
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(*dev_args, num_rows)
            _ = np.asarray(out[-1])
            best = min(best, time.perf_counter() - t0)
        return best, out

    t_short, _ = measure(1)
    t_long, out = measure(1 + ITERS)
    tpu_s = max((t_long - t_short) / ITERS, 1e-9)

    t0 = time.perf_counter()
    ref = host_baseline(key_vals, key_valid, val_vals, val_valid, N_ROWS)
    cpu_s = time.perf_counter() - t0

    # correctness spot-check: group count and total sum match the host baseline
    n_groups = int(out[-1])
    assert n_groups == len(ref[0]), (n_groups, len(ref[0]))
    dev_sum = float(np.asarray(out[1])[:n_groups].sum())
    assert abs(dev_sum - float(ref[1].sum())) < 1e-6 * max(1.0, abs(dev_sum))

    rows_per_s = N_ROWS / tpu_s
    line = {
        "metric": "fused_hash_aggregate_throughput",
        "value": round(rows_per_s / 1e6, 3),
        "unit": "Mrows/s",
        "vs_baseline": round(cpu_s / tpu_s, 3),
    }
    if platform != "tpu":
        line["degraded"] = f"platform={platform}"
    print(json.dumps(line))


def _spawn(extra_env, timeout_s):
    """Run this script as a measuring child; return its last JSON line or None."""
    env = dict(os.environ)
    env.update(extra_env)
    env["_SRT_BENCH_CHILD"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=timeout_s)
    except subprocess.TimeoutExpired as e:
        out = e.stdout
        if isinstance(out, bytes):
            out = out.decode("utf-8", "replace")
        return None, f"timeout after {timeout_s}s: {(out or '')[-2000:]}"
    tail = (proc.stdout or "")[-2000:]
    for ln in reversed((proc.stdout or "").splitlines()):
        ln = ln.strip()
        if ln.startswith("{") and ln.endswith("}"):
            try:
                parsed = json.loads(ln)
                if "metric" in parsed:
                    return parsed, tail
            except (ValueError, TypeError):
                continue
    return None, f"rc={proc.returncode}: {tail}"


def _probe_backend():
    """Is the accelerator backend usable at all? Short subprocess probe."""
    code = ("import jax; d = jax.devices(); "
            "import jax.numpy as jnp; "
            "x = jnp.ones((8,)) + 1; x.block_until_ready(); "
            "print('PROBE_OK', d[0].platform)")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=dict(os.environ),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=PROBE_TIMEOUT_S)
        return proc.returncode == 0 and "PROBE_OK" in (proc.stdout or "")
    except subprocess.TimeoutExpired:
        return False


def parent_main():
    """Never exits non-zero; always prints one JSON line."""
    attempts = []
    # accelerator path: probe, then measure, with one retry
    for attempt in range(2):
        if _probe_backend():
            parsed, err = _spawn({}, CHILD_TIMEOUT_S)
            if parsed is not None:
                print(json.dumps(parsed))
                return
            attempts.append(f"accel attempt {attempt}: {err}")
        else:
            attempts.append(f"accel probe {attempt}: backend unavailable")
        if attempt == 0:
            time.sleep(10)
    # degraded path: force CPU so the metric is never null
    parsed, err = _spawn({"JAX_PLATFORMS": "cpu"}, CHILD_TIMEOUT_S)
    if parsed is not None:
        parsed["degraded"] = "cpu-fallback: " + "; ".join(attempts)[-500:]
        print(json.dumps(parsed))
        return
    attempts.append(f"cpu fallback: {err}")
    print(json.dumps({
        "metric": "fused_hash_aggregate_throughput",
        "value": 0.0,
        "unit": "Mrows/s",
        "vs_baseline": 0.0,
        "degraded": "; ".join(attempts)[-900:],
    }))


if __name__ == "__main__":
    if os.environ.get("_SRT_BENCH_CHILD") == "1":
        child_main()
    else:
        parent_main()
