#!/usr/bin/env bash
# Single entry point for CI and local premerge (reference premerge scripts role).
set -euo pipefail
cd "$(dirname "$0")"

echo "== unit + integration suite (virtual 8-device CPU mesh) =="
python -m pytest tests/ -q

echo "== driver entry points =="
JAX_PLATFORMS=cpu python -c "
import jax; jax.config.update('jax_platforms', 'cpu')
import __graft_entry__ as g
fn, args = g.entry()
out = jax.jit(fn)(*args)
assert out is not None
print('entry() ok')"
python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"

echo "== api coverage gate (0 missing vs reference GpuOverrides) =="
python tools/api_validation.py 0 0

echo "== config docs in sync =="
python -m spark_rapids_tpu.config
git diff --exit-code docs/configs.md || {
  echo "docs/configs.md out of date: run python -m spark_rapids_tpu.config"; exit 1; }

echo "CI OK"
