#!/usr/bin/env bash
# Single entry point for CI and local premerge (reference premerge scripts role).
set -euo pipefail
cd "$(dirname "$0")"

echo "== unit + integration suite (virtual 8-device CPU mesh) =="
python -m pytest tests/ -q

echo "== driver entry points =="
JAX_PLATFORMS=cpu python -c "
import jax; jax.config.update('jax_platforms', 'cpu')
import __graft_entry__ as g
fn, args = g.entry()
out = jax.jit(fn)(*args)
assert out is not None
print('entry() ok')"
python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"

echo "== on-chip tool dry-runs (CPU platform; round-4 postmortem gate) =="
# The one TPU window round 4 got was burned by an untested child process
# (ModuleNotFoundError). Run the EXACT subprocess invocations the watcher
# uses, end-to-end, on the CPU platform, so they can never regress unseen.
python tools/tpu_correctness.py --dryrun-cpu --out /tmp/ci_tpu_correctness.json
python - <<'PYEOF'
import json
d = json.load(open("/tmp/ci_tpu_correctness.json"))
assert d["ok"] and d["platform"] == "cpu", d
print("correctness dry-run ok:", len(d["checks"]), "checks")
PYEOF
# bench measuring child, exact _spawn() invocation at tiny scale
bench_line=$(_SRT_BENCH_CHILD=1 JAX_PLATFORMS=cpu TPCH_SF=0.01 \
  TPCH_DIR=/tmp/tpch_ci_sf0.01 TPCDS_SECONDARY=0 python bench.py | tail -1)
python -c '
import json, sys
d = json.loads(sys.argv[1])
assert "metric" in d and d["value"] > 0, d
assert "spread" in d and "queries" in d, d
# with no faults configured the retry spine AND the cluster recovery
# ladder must be invisible: every resilience counter zero — the
# memoryLeakedBuffers counter riding here makes leak-freedom a standing
# invariant of every no-faults bench
assert not any(d["resilience"].values()), d["resilience"]
# compile/retrace telemetry: whole-process totals plus per-query hot-rep
# deltas (the retrace denominator for the fusion roadmap gate)
assert d["compiles"] > 0 and d["dispatches"] > 0, d
for q, pq in d["queries"].items():
    assert "compiles" in pq and "dispatches" in pq, (q, pq)
    # memory trajectory: every per-query entry records its device
    # high-water mark and the allocation site that owned it
    assert pq.get("peak_device_bytes", 0) > 0, (q, pq)
    assert pq.get("top_alloc_site"), (q, pq)
    # statistics plane: every per-query entry carries the footprint
    # estimate error (no history dir here, so hits must be False)
    assert pq.get("estimate_error") is not None, (q, pq)
    assert pq.get("history_hit") is False, (q, pq)
print("bench-child dry-run ok:", d["metric"], d["value"], d["unit"],
      "spread", d["spread"], "resilience", d["resilience"],
      "hot-rep compiles",
      {q: pq["compiles"] for q, pq in d["queries"].items()},
      "peak_dev", {q: pq["peak_device_bytes"] for q, pq in d["queries"].items()})
' "$bench_line"
# perf-trajectory soft gate: compare the line against the committed
# baseline (warn >10%, fail >25% geomean regression of the per-query
# oracle-normalized scores). The sf0.01 CI dry-run is NOT comparable to
# the committed sf0.1 line, so this prints the SKIP reason here; round
# drivers comparing same-scale lines get the real gate
echo "$bench_line" > /tmp/ci_bench_line.json
python tools/bench_compare.py /tmp/ci_bench_line.json --baseline BENCH_r08.json

echo "== radix spine: kernel interpret tests + join microbench smoke =="
# the exact kernel set the next chip window's probe latch will exercise,
# plus the join-spine microbench in smoke mode — parity of the Pallas
# probe against the lax.sort rank path is a gate, not a hope
JAX_PLATFORMS=cpu python -m pytest tests/test_pallas.py \
  tests/test_readahead.py -q
micro_line=$(JAX_PLATFORMS=cpu python bench.py --join-micro --smoke | tail -1)
python -c '
import json, sys
d = json.loads(sys.argv[1])
assert d["parity_ok"] and d["matches"] > 0, d
print("join microbench smoke ok: pallas probe", d["pallas_probe_ms"],
      "ms vs laxsort rank", d["laxsort_rank_ms"], "ms")
' "$micro_line"

echo "== chaos: task-scoped OOM retry + deterministic fault injection =="
# fast chaos gate (fixed fault seeds inside the suite, so the injection
# schedule can never drift between runs): injected join-build OOMs and
# dropped fetches must recover to bit-identical results, with the recovery
# visible in the resilience counters
JAX_PLATFORMS=cpu python -m pytest tests/test_retry_faults.py -q

echo "== pipelined executor: q18 A/B gate + chaos with the pipeline on =="
# overlap of decode / device compute / exchange I/O needs real parallelism:
# on <2 cores the gate auto-skips (with the reason logged); on a multi-core
# box q18 with pipeline.enabled=true must beat enabled=false by >=1.15x
# (median of 5, the bench ladder's query + reader config), bit-identically
JAX_PLATFORMS=cpu python - <<'PYEOF'
import os
cores = os.cpu_count() or 1
if cores < 2:
    print(f"pipeline A/B gate SKIPPED: {cores} core(s) — "
          "decode/compute/exchange overlap needs >=2 cores")
    raise SystemExit(0)
import jax; jax.config.update("jax_platforms", "cpu")
import statistics, time
import spark_rapids_tpu  # noqa: F401  (enables x64)
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.session import TpuSession

paths = tpch.generate(0.01, "/tmp/tpch_ci_sf0.01")

def run(pipeline_on):
    spark = TpuSession({
        "spark.rapids.tpu.sql.format.parquet.reader.type": "COALESCING",
        "spark.rapids.tpu.pipeline.enabled": pipeline_on})
    dfs = tpch.load(spark, paths, files_per_partition=4)
    df = tpch.q18(dfs)
    rows = df.collect().to_pylist()     # warm (compiles cached after)
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        df.collect()
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts), rows

on_s, on_rows = run(True)
off_s, off_rows = run(False)
assert on_rows == off_rows, "pipeline on/off results differ"
speedup = off_s / on_s
print(f"pipeline gate: q18 off={off_s:.4f}s on={on_s:.4f}s "
      f"({speedup:.2f}x, {cores} cores)")
assert speedup >= 1.15, f"pipeline speedup {speedup:.2f}x < 1.15x"
PYEOF
# chaos once with the pipeline explicitly on: an injected worker-thread
# decode fault must fail cleanly (no leaked registrations/threads) and an
# injected split-OOM inside a pipeline segment must recover bit-identically
JAX_PLATFORMS=cpu python -m pytest tests/test_pipeline.py -q

echo "== whole-stage chain fusion: >=3x per-batch dispatch drop, bit-identical =="
# the broadcast-join probe chains (q18's agg->orders->customer shape, q5's
# orders->customer hops) must collapse to ~1 dispatch per stream batch: the
# chain-region dispatch count (the spine of BHJ/Project/Filter nodes the
# chain absorbed) drops >=3x vs stageFusion.enabled=false, with bit-identical
# rows. q18's canonical HAVING>300 yields 0 rows at SF 0.01 (no emits to
# save on the unfused side), so the flowing-rows ratio is asserted on q5 and
# on q18's own plan shape with the threshold lowered; canonical q18 asserts
# chain formation + bit-identity.
JAX_PLATFORMS=cpu python - <<'PYEOF'
import jax; jax.config.update("jax_platforms", "cpu")
import spark_rapids_tpu  # noqa: F401  (enables x64)
import spark_rapids_tpu.functions as F
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.runtime import stats as STATS

# 12 files -> 12 stream batches: enough for the per-hop one-off build-prep
# dispatches to amortize out of the region ratio
paths = tpch.generate(0.01, "/tmp/tpch_ci_sf0.01_f12", files_per_table=12)
c = F.col

def q18_flow(dfs):
    # q18's exact plan shape with the HAVING threshold lowered so every
    # stream batch carries matches through both probe hops
    li = dfs["lineitem"]
    big = (li.group_by(c("l_orderkey"))
           .agg(F.sum(c("l_quantity")).alias("sum_qty"))
           .filter(c("sum_qty") > F.lit(30.0)))
    orders = dfs["orders"].select(
        c("o_orderkey").alias("l_orderkey"), c("o_custkey"),
        c("o_orderdate"), c("o_totalprice"))
    cust = dfs["customer"].select(c("c_custkey").alias("o_custkey"))
    return big.join(orders, on="l_orderkey").join(cust, on="o_custkey")

def chain_region(root):
    # unfused: the stream spine the chain would absorb (topmost BHJ down
    # through stream children over BHJ/Project/Filter, excluding the scan)
    def find(n):
        if type(n).__name__ == "BroadcastHashJoinExec":
            return n
        for ch in n.children:
            r = find(ch)
            if r is not None:
                return r
    n, out = find(root), []
    while type(n).__name__ in ("BroadcastHashJoinExec", "ProjectExec",
                               "FilterExec"):
        out.append(n)
        si = ((0 if n.stream_is_left else 1)
              if type(n).__name__ == "BroadcastHashJoinExec" else 0)
        n = n.children[si]
    return out

def find_chain(n):
    if type(n).__name__ == "BroadcastHashJoinChainExec":
        return n
    for ch in n.children:
        r = find_chain(ch)
        if r is not None:
            return r

def run(make_df, fusion):
    spark = TpuSession({"spark.rapids.tpu.sql.stageFusion.enabled": fusion})
    dfs = tpch.load(spark, paths, files_per_partition=12)
    df = make_df(dfs)
    df.collect()                        # warm: traces + capacity predictions
    rows = sorted(map(tuple, (r.values()
                              for r in df.collect().to_pylist())))
    cl = df._last_collector
    disp = {e["id"]: e["dispatches"] or 0 for e in STATS.node_table(cl)}
    if fusion:
        chain = find_chain(cl.root)
        assert chain is not None, "no chain formed"
        return rows, disp[chain._node_id]
    assert find_chain(cl.root) is None, "chain formed with fusion off"
    return rows, sum(disp.get(n._node_id, 0) for n in chain_region(cl.root))

for name, make_df in (("q5", tpch.q5), ("q18-flow", q18_flow)):
    r_on, reg_on = run(make_df, True)
    r_off, reg_off = run(make_df, False)
    assert r_on == r_off, f"{name}: fused rows differ"
    assert len(r_on) > 0, f"{name}: no rows flowed through the chain"
    ratio = reg_off / max(reg_on, 1)
    print(f"chain gate: {name} region dispatches unfused={reg_off} "
          f"fused={reg_on} ({ratio:.2f}x)")
    assert ratio >= 3.0, f"{name}: chain dispatch drop {ratio:.2f}x < 3x"

# canonical q18 (empty output at this SF): chain forms, rows bit-identical
r_on, _ = run(tpch.q18, True)
r_off, _ = run(tpch.q18, False)
assert r_on == r_off, "q18: fused rows differ"
print("chain gate: q18 canonical bit-identical (chain formed)")
PYEOF

echo "== persistent stage cache: warm-start q18 replays with 0 traces =="
# cross-process contract: a fresh session pointed at a populated cache dir
# must replay every fused stage from serialized executables — zero Python
# retraces, zero XLA compiles (each heredoc below is its own process)
stage_cache_dir=$(mktemp -d /tmp/srt_stagecache.XXXXXX)
for phase in populate replay; do
SRT_CI_PHASE="$phase" SRT_CI_CACHE_DIR="$stage_cache_dir" \
JAX_PLATFORMS=cpu python - <<'PYEOF'
import os
import jax; jax.config.update("jax_platforms", "cpu")
import spark_rapids_tpu  # noqa: F401  (enables x64)
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.runtime import fuse, stage_cache

phase = os.environ["SRT_CI_PHASE"]
paths = tpch.generate(0.01, "/tmp/tpch_ci_sf0.01")
spark = TpuSession({
    "spark.rapids.tpu.sql.stage.cache.enabled": True,
    "spark.rapids.tpu.sql.stage.cache.dir": os.environ["SRT_CI_CACHE_DIR"]})
dfs = tpch.load(spark, paths, files_per_partition=4)
tpch.q18(dfs).collect()
st = stage_cache.get()
traces = fuse.stage_metrics()["traces"]
print(f"stage-cache gate [{phase}]: traces={traces} hits={st.hits} "
      f"saves={st.saves}")
if phase == "populate":
    assert st.saves > 0, "populate session saved no stage executables"
else:
    assert traces == 0, f"warm-start q18 retraced {traces} stages"
    assert st.hits > 0, "warm-start session hit no cache entries"
PYEOF
done
rm -rf "$stage_cache_dir"

echo "== scan-side chain: bit-identity + warm-start replay of fused scan stages =="
# the scan-floor gate (perf_notes r9): q1 and q18 with the full scan-side
# chain on (device decode + encoded upload + fused decode→filter→partial-agg
# + chained group-by) must be bit-identical to the arrow path, and a FRESH
# process pointed at the populated stage cache must replay every fused scan
# stage (EncodedCol signatures included) with zero Python retraces
scan_cache_dir=$(mktemp -d /tmp/srt_scancache.XXXXXX)
for phase in populate replay; do
SRT_CI_PHASE="$phase" SRT_CI_CACHE_DIR="$scan_cache_dir" \
JAX_PLATFORMS=cpu python - <<'PYEOF'
import os
import jax; jax.config.update("jax_platforms", "cpu")
import spark_rapids_tpu  # noqa: F401  (enables x64)
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.runtime import fuse, stage_cache

phase = os.environ["SRT_CI_PHASE"]
paths = tpch.generate(0.01, "/tmp/tpch_ci_sf0.01_f12", files_per_table=12)
ON = {
    "spark.rapids.tpu.sql.stageFusion.enabled": True,
    "spark.rapids.tpu.sql.parquet.deviceDecode.enabled": True,
    "spark.rapids.tpu.sql.parquet.encodedUpload.enabled": True,
    "spark.rapids.tpu.sql.stage.cache.enabled": True,
    "spark.rapids.tpu.sql.stage.cache.dir": os.environ["SRT_CI_CACHE_DIR"]}

def run(query, conf):
    spark = TpuSession(dict(conf))
    dfs = tpch.load(spark, paths, files_per_partition=3)
    return tpch.QUERIES[query](dfs).collect().to_pylist()

if phase == "populate":
    for q in ("q1", "q18"):
        on = run(q, ON)
        off = run(q, {
            "spark.rapids.tpu.sql.stageFusion.enabled": False,
            "spark.rapids.tpu.sql.parquet.deviceDecode.enabled": False})
        assert on == off, f"{q}: encoded scan-chain rows differ from arrow"
    st = stage_cache.get()
    print(f"scan gate [populate]: q1/q18 bit-identical, saves={st.saves}")
    assert st.saves > 0, "populate session saved no stage executables"
else:
    run("q1", ON)
    run("q18", ON)
    traces = fuse.stage_metrics()["traces"]
    st = stage_cache.get()
    print(f"scan gate [replay]: traces={traces} hits={st.hits}")
    assert traces == 0, f"warm-start fused scan stages retraced {traces}"
    assert st.hits > 0, "warm-start session hit no cache entries"
PYEOF
done
rm -rf "$scan_cache_dir"

echo "== scan-side chain: encoded-upload h2d pricing via profiler.py movement =="
# the movement read-out must PRICE the win: q1 (scan-heavy, dictionary-
# friendly columns) re-run with dense device upload moves >=1.3x the PCIe
# bytes of the encoded run, as replayed from the event logs by the
# profiler's movement plane — the gate reads the TOOL, not the in-process
# ledger, so the read-out path itself stays honest
scan_mv_enc=$(mktemp -d)
scan_mv_den=$(mktemp -d)
for mode in enc den; do
if [ "$mode" = enc ]; then obs="$scan_mv_enc"; else obs="$scan_mv_den"; fi
SRT_CI_MODE="$mode" SRT_OBS_DIR="$obs" JAX_PLATFORMS=cpu python - <<'PYEOF'
import os
import jax; jax.config.update("jax_platforms", "cpu")
import spark_rapids_tpu  # noqa: F401  (enables x64)
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.runtime import eventlog

paths = tpch.generate(0.01, "/tmp/tpch_ci_sf0.01_f12", files_per_table=12)
spark = TpuSession({
    "spark.rapids.tpu.sql.stageFusion.enabled": True,
    "spark.rapids.tpu.sql.parquet.deviceDecode.enabled": True,
    "spark.rapids.tpu.sql.parquet.encodedUpload.enabled":
        os.environ["SRT_CI_MODE"] == "enc",
    "spark.rapids.tpu.eventLog.dir": os.environ["SRT_OBS_DIR"],
    "spark.rapids.tpu.movement.sample.intervalBytes": "64k"})
dfs = tpch.load(spark, paths, files_per_partition=3)
tpch.QUERIES["q1"](dfs).collect()
eventlog.shutdown()
PYEOF
done
for d in "$scan_mv_enc" "$scan_mv_den"; do
  python tools/profiler.py movement "$d"/events-*.jsonl --json \
    > "$d/movement.json"
done
python - "$scan_mv_enc/movement.json" "$scan_mv_den/movement.json" <<'PYEOF'
import json, sys

def h2d(p):
    m = json.load(open(p))
    return sum(f["bytes"] for f in m["flows"] if f["edge"] == "h2d")

enc, den = h2d(sys.argv[1]), h2d(sys.argv[2])
ratio = den / max(enc, 1)
print(f"scan movement gate: q1 h2d dense={den}B encoded={enc}B "
      f"({ratio:.2f}x)")
assert enc > 0, "no h2d flow in the encoded run's movement plane"
assert ratio >= 1.3, f"encoded upload h2d drop {ratio:.2f}x < 1.3x"
PYEOF
rm -rf "$scan_mv_enc" "$scan_mv_den"

echo "== cluster chaos: executor kill mid-q18 on a 3-executor MiniCluster =="
# losing 1 of 3 executors mid-query must cost ~1/N of a stage, not the
# query: the killed run must be bit-identical to the clean run, recompute
# strictly fewer map tasks than a full re-run, never reach the whole-query
# heal fallback, and leave the recovery ladder visible in the event log
# a real script file, not a heredoc: the spawn-based executor bootstrap
# re-imports __main__, and stdin cannot be re-imported
chaos_dir=$(mktemp -d)
JAX_PLATFORMS=cpu python tools/cluster_chaos.py \
  --data-dir /tmp/tpch_ci_sf0.01 --eventlog-dir "$chaos_dir" --query q18
# executors write their own events-*.jsonl (clock-offset-stamped) into the
# same dir now; the ladder assertions read the DRIVER's file, identified by
# the driver-only executor.lost event
chaos_log=$(grep -l "executor.lost" "$chaos_dir"/events-*.jsonl | head -1)
python - "$chaos_log" <<'PYEOF'
import json, sys
events = [json.loads(ln)["event"] for ln in open(sys.argv[1]) if ln.strip()]
assert "executor.lost" in events, sorted(set(events))
assert events.count("stage.recompute.partial") >= 1, sorted(set(events))
print("chaos event log ok:", events.count("executor.lost"),
      "executor.lost,", events.count("stage.recompute.partial"),
      "stage.recompute.partial")
PYEOF
# the profiler's recovery table must replay the ladder from the same log
# (rc is not gated here: the cluster driver emits no per-query operator
# breakdown, which the report treats as an error for SESSION logs)
python tools/profiler.py report "$chaos_log" > /tmp/chaos_profile.txt || true
grep -q "recovery (task attempt" /tmp/chaos_profile.txt
grep -q "partial recompute shuffle=" /tmp/chaos_profile.txt
# distributed trace of the SAME 3-executor q18 chaos run: the per-process
# span files (driver + executors + the respawned incarnation) must merge
# into one Perfetto-loadable Chrome trace sharing the query's trace id,
# and the critical-path table must be non-empty and name a bounding edge
python tools/profiler.py trace "$chaos_dir" --out /tmp/chaos_trace.json \
  > /tmp/chaos_trace.txt
grep -q "critical path" /tmp/chaos_trace.txt
grep -q "bounding edge:" /tmp/chaos_trace.txt
python - /tmp/chaos_trace.json <<'PYEOF'
import json, sys
t = json.load(open(sys.argv[1]))
evs = [e for e in t["traceEvents"] if e["ph"] != "M"]
meta = [e for e in t["traceEvents"] if e["ph"] == "M"]
assert evs and meta, (len(evs), len(meta))
for e in evs:
    assert {"name", "ph", "ts", "pid", "tid"} <= set(e), e
    assert e["ph"] != "X" or "dur" in e, e
pids = {e["pid"] for e in evs}
# counter samples (ph C) carry numeric series only, no trace-id arg
traces = {e["args"].get("trace") for e in evs
          if e.get("args") and e["ph"] != "C"}
assert len(pids) >= 2, pids      # driver + executor lanes
assert len(traces) == 1, traces  # every span carries the query's trace id
# executor MEMORY lanes: the merged trace must carry per-process memory
# counter tracks from >=2 processes (executors allocate shuffle blobs in
# their own catalogs; their samples ride the same span files)
mem_pids = {e["pid"] for e in evs
            if e["ph"] == "C" and e["name"] == "memory"}
assert len(mem_pids) >= 2, ("memory counter lanes", mem_pids)
print("chaos chrome trace ok:", len(evs), "events from", len(pids),
      "processes, trace", traces.pop(), "memory lanes from",
      len(mem_pids), "processes")
PYEOF
# a malformed span file must fail the trace export loudly
bad_dir=$(mktemp -d); echo '{broken json' > "$bad_dir/spans-1-x.jsonl"
if python tools/profiler.py trace "$bad_dir" >/dev/null 2>&1; then
  echo "profiler trace accepted a malformed span file"; exit 1
fi
rm -rf "$bad_dir"
rm -rf "$chaos_dir"

echo "== mesh-cluster chaos: unified plane, mesh kill mid-q18 -> degraded TCP fallback =="
# the combined N-process x M-chip plane (ROADMAP item 4): a 2-executor
# MiniCluster, each executor driving a 4-device local mesh. The script
# asserts the whole contract: the CLEAN mesh run used mesh tasks with every
# resilience counter zero (meshDegradedFallbacks rides the all-zero gate),
# and the killed run — a participant SIGKILLed INSIDE the mesh collective —
# degraded its group to the per-split TCP path under a bumped epoch,
# recomputed earlier stages' lost splits lineage-scoped, never reached the
# whole-query heal, and stayed bit-identical
mesh_dir=$(mktemp -d)
JAX_PLATFORMS=cpu python tools/cluster_chaos.py \
  --data-dir /tmp/tpch_ci_sf0.01 --eventlog-dir "$mesh_dir" --query q18 \
  --mesh --executors 2
# the degraded-mode ladder must be visible in the DRIVER's event log
mesh_log=$(grep -l "mesh.degraded" "$mesh_dir"/events-*.jsonl | head -1)
python - "$mesh_log" <<'PYEOF'
import json, sys
events = [json.loads(ln)["event"] for ln in open(sys.argv[1]) if ln.strip()]
for want in ("mesh.attach", "mesh.detach", "mesh.degraded", "executor.lost"):
    assert want in events, (want, sorted(set(events)))
print("mesh chaos event log ok:",
      events.count("mesh.attach"), "mesh.attach,",
      events.count("mesh.degraded"), "mesh.degraded,",
      events.count("mesh.detach"), "mesh.detach")
PYEOF
rm -rf "$mesh_dir"
# mesh-plane unit/integration suite: wave pid bit-exactness vs the
# per-batch partitioner, kill/hang/error degraded fallbacks,
# movement-aware placement + spill-aware demotion, the typed-ENOSPC OOM
# ladder, and spawn-handshake retry
JAX_PLATFORMS=cpu python -m pytest tests/test_mesh_cluster.py -q -m 'not slow'

echo "== two-level exchange: intra-mesh content over ICI (movement gate) =="
# q18 twice on a 2-executor x 4-chip mesh cluster (child processes, so the
# cumulative per-process ledgers stay separable): twoLevel=off vs on must
# show >=2x fewer loopback/TCP shuffle payload bytes with the savings
# appearing on the ici.collective edge, and bit-identical result digests
tl_dir=$(mktemp -d)
JAX_PLATFORMS=cpu python tools/movement_gate.py \
  --data-dir /tmp/tpch_ci_sf0.01 --eventlog-dir "$tl_dir" --query q18 \
  --executors 2 --two-level-compare
# the profiler read-out separates the two exchange levels at a glance
python tools/profiler.py movement "$tl_dir"/twolevel-on/events-*.jsonl \
  > /tmp/tl_readout.txt
grep -q "exchange levels:" /tmp/tl_readout.txt
grep -q "intra-mesh(ici)=" /tmp/tl_readout.txt
rm -rf "$tl_dir"

echo "== sf1 q18 out-of-core completion smoke (>=2 executors) =="
# the scale-out proof: q18 at sf1 completes on 2 executors with BOTH
# memory tiers shrunk below the working set (device -> host -> disk
# spill asserted from the ledger), two-level exchange on; auto-skip
# (logged) on a 1-core box, per the gate's >=2-executor contract
if [ "$(nproc)" -ge 2 ]; then
  ooc_dir=$(mktemp -d)
  JAX_PLATFORMS=cpu python tools/movement_gate.py \
    --data-dir /tmp/tpch_ci_sf1 --eventlog-dir "$ooc_dir" --query q18 \
    --executors 2 --ooc-smoke --scale 1.0 --ooc-limit 256m
  rm -rf "$ooc_dir"
else
  echo "SKIP: sf1 out-of-core smoke needs >=2 cores, have $(nproc)"
fi

echo "== multi-tenant: concurrent chaos (cancel + OOM + shed isolation) =="
# 4 concurrent TPC-H queries: one killed by its deadline, one recovering
# injected join-build OOMs, two survivors bit-identical to solo runs with
# EVERY query-scoped resilience counter zero; a 5th submission sheds with a
# pickle-round-tripped backoff hint; nothing leaks (threads/buffers/permits)
mt_dir=$(mktemp -d)
JAX_PLATFORMS=cpu python tools/concurrent_chaos.py \
  --data-dir /tmp/tpch_ci_sf0.01 --eventlog-dir "$mt_dir"
mt_log=$(ls "$mt_dir"/*.jsonl | head -1)
python - "$mt_log" <<'PYEOF'
import json, sys
events = [json.loads(ln)["event"] for ln in open(sys.argv[1]) if ln.strip()]
# all four lifecycle outcomes visible in one log: admitted queries, the
# deadline kill, the shed submission (after queueing), and the OOM recovery
for want in ("query.admitted", "query.deadline", "query.queued",
             "query.shed", "oom.retry", "query.end"):
    assert want in events, (want, sorted(set(events)))
print("multi-tenant event log ok:",
      events.count("query.admitted"), "admitted,",
      events.count("query.deadline"), "deadline,",
      events.count("query.shed"), "shed,",
      events.count("oom.retry"), "oom.retry")
PYEOF
# the profiler renders the admission/lifecycle table from the same log
python tools/profiler.py report "$mt_log" > /tmp/mt_profile.txt || true
grep -q "admission / lifecycle" /tmp/mt_profile.txt
grep -q "deadline q" /tmp/mt_profile.txt
grep -q "shed " /tmp/mt_profile.txt
rm -rf "$mt_dir"
# scheduler + lifecycle unit/integration suite (cancellation leak checks,
# admission, shed round-trip, CRC corruption ladders, eventlog rotation)
JAX_PLATFORMS=cpu python -m pytest tests/test_scheduler.py -q

echo "== multi-tenant: concurrent aggregate-throughput gate =="
# 4 concurrent q18s through the admission scheduler must beat 4 sequential
# runs by >=1.2x aggregate on >=2 cores (overlap of scan decode, device
# compute and exchange I/O ACROSS queries); the 1-core box auto-skips with
# the reason logged. Isolation is asserted unconditionally: bit-identical
# rows, distinct query ids, zero scoped resilience counters
conc_line=$(JAX_PLATFORMS=cpu TPCH_SF=0.01 TPCH_DIR=/tmp/tpch_ci_sf0.01 \
  python bench.py --concurrent 4 | tail -1)
python -c '
import json, sys
d = json.loads(sys.argv[1])
assert d["isolation_ok"], d
# per-priority latency percentiles from the new fixed-bucket histograms
# must be embedded and internally consistent (p50 <= p95 <= p99)
lat = d["latency"]
assert any(k.startswith("priority") for k in lat), lat
for k, v in lat.items():
    if k.startswith("priority"):
        assert v["p50"] <= v["p95"] <= v["p99"], (k, v)
        assert v["count"] >= d["n"], (k, v)
if "gate_skipped" in d:
    print("concurrent throughput gate SKIPPED:", d["gate_skipped"],
          "(measured", d["throughput_x"], "x)")
else:
    assert d["throughput_x"] >= 1.2, d
    print("concurrent throughput gate ok:", d["throughput_x"], "x on",
          d["cores"], "cores,", "p50/p95/p99", lat)
' "$conc_line"

echo "== serving endpoint: wire chaos (mid-stream kill + shed + SIGTERM drain) =="
# concurrent clients against the Arrow-over-TCP endpoint: one client killed
# while its query is in flight (disconnect → CancelToken → clean drain), a
# submission shed over the wire with its backoff hint arriving typed, then
# a real SIGTERM drain under load — the in-flight query finishes
# bit-identically, a mid-drain submission sheds with reason=draining, and
# nothing leaks (threads/buffers/permits)
ep_dir=$(mktemp -d)
JAX_PLATFORMS=cpu python tools/endpoint_chaos.py \
  --data-dir /tmp/tpch_ci_sf0.01 --eventlog-dir "$ep_dir"
ep_log=$(ls "$ep_dir"/*.jsonl | head -1)
python - "$ep_log" <<'PYEOF'
import json, sys
events = [json.loads(ln)["event"] for ln in open(sys.argv[1]) if ln.strip()]
for want in ("endpoint.start", "client.connected", "client.disconnected",
             "query.cancelled", "query.shed", "server.drain",
             "endpoint.stop"):
    assert want in events, (want, sorted(set(events)))
print("endpoint event log ok:",
      events.count("client.connected"), "connected,",
      events.count("client.disconnected"), "disconnected,",
      events.count("query.shed"), "shed,",
      events.count("server.drain"), "server.drain")
PYEOF
rm -rf "$ep_dir"
# endpoint + transport unit/integration suite (frame fuzz, CRC corruption,
# disconnect cancellation both FIN and RST, drain, exception pickles)
JAX_PLATFORMS=cpu python -m pytest tests/test_endpoint.py \
  tests/test_transport.py -q

echo "== serving endpoint: no-faults concurrent bench through the wire =="
# N concurrent clients through the endpoint with no faults armed: isolation
# evidence from the wire's summary frames, and EVERY process-wide resilience
# counter zero — serving through the front door must be invisible to the
# recovery ladders (including the endpoint's own disconnect counter)
ep_line=$(JAX_PLATFORMS=cpu TPCH_SF=0.01 TPCH_DIR=/tmp/tpch_ci_sf0.01 \
  python bench.py --concurrent 2 --endpoint --query q5 | tail -1)
python -c '
import json, sys
d = json.loads(sys.argv[1])
assert d["endpoint"] and d["isolation_ok"], d
assert not any(d["resilience"].values()), d["resilience"]
print("endpoint bench ok:", d["metric"], "throughput", d["throughput_x"], "x")
' "$ep_line"

echo "== serving fleet: chaos gate (warm replicas, SIGKILL failover, lease adoption) =="
# three real replica PROCESSES behind one fleet directory + shared stage
# cache: replica A compiles the workload, a fresh replica B serves the same
# shapes with ZERO retraces; a no-faults fleet load keeps every resilience
# counter zero on both replicas; a victim replica is SIGKILLed mid-stream
# and the client's submit_with_retry fails over to a survivor
# bit-identically; a survivor adopts the victim's expired lease and
# reclaims its orphaned shared-store write intents. The fleet observability
# plane gates inside the same harness: the victim's blackbox dump survives
# the SIGKILL naming the in-flight query, the survivor's fleet.adopt
# carries the dump path, profiler.py journey renders the cross-replica
# failover timeline with rc=0, profiler.py fleet lists the dead victim's
# tombstone, and the fleet-stats aggregate equals an independent re-sum of
# every replica's raw counters
fleet_dir=$(mktemp -d)
JAX_PLATFORMS=cpu python tools/fleet_chaos.py --work-dir "$fleet_dir"
rm -rf "$fleet_dir"
# fleet membership / journey / blackbox / client rotation / result-cache
JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py tests/test_fleet_observability.py -q

echo "== serving fleet: 2-replica throughput through the wire =="
# 2 replica processes sharing one compiled-stage cache: n concurrent
# clients spread across the fleet must beat n sequential submissions
# through ONE replica by >=1.5x on a multi-core box (on 1 core the line
# carries gate_skipped and the assertion is skipped with the reason
# logged); the client-side resilience snapshot must stay all-zero — load
# spreading is routing, not recovery
fleet_line=$(JAX_PLATFORMS=cpu TPCH_SF=0.01 TPCH_DIR=/tmp/tpch_ci_sf0.01 \
  python bench.py --concurrent 2 --endpoint --replicas 2 --query q5 | tail -1)
python -c '
import json, sys
d = json.loads(sys.argv[1])
assert d["endpoint"] and d["replicas"] == 2 and d["isolation_ok"], d
assert not any(d["resilience"].values()), d["resilience"]
# serving-latency trajectory: journey counts + fleet percentiles must be
# embedded (bench_compare diffs them), and a no-faults run serves every
# journey without a single failover hop
assert d["journeys"] and all(
    j["failover"] == 0 for j in d["journeys"].values()), d["journeys"]
assert sum(j["served"] + j["cached"]
           for j in d["journeys"].values()) >= d["n"], d["journeys"]
assert d["fleet_latency"]["p50"] and d["fleet_latency"]["p99"], d
if "gate_skipped" in d:
    print("fleet throughput gate SKIPPED:", d["gate_skipped"],
          "| measured", d["throughput_x"], "x")
else:
    assert d["throughput_x"] >= 1.5, d
    print("fleet throughput gate ok:", d["throughput_x"], "x on",
          d["cores"], "cores")
' "$fleet_line"

echo "== streaming: exactly-once epoch chaos (kill mid-commit, bit-identical replay) =="
# a >=20-epoch windowed-agg stream through the epoch coordinator: state
# rows/bytes must stay FLAT under the watermark (retirement works), the
# steady-state tail must run with zero compiles, a child coordinator
# SIGKILLed by exec_kill INSIDE the commit window must replay its epoch
# bit-identically at attempt 2 (streamEpochReplays counted exactly once),
# the profiler's streaming read-out must schema-validate the journal (and
# reject a corrupted copy), and a single-giant-epoch oracle must reproduce
# the exact final state + checksum (merge associativity cross-check)
stream_dir=$(mktemp -d)
JAX_PLATFORMS=cpu python tools/stream_chaos.py --work-dir "$stream_dir"
rm -rf "$stream_dir"
# streaming unit/integration suite: journal fencing + corruption refusal,
# CRC-verified idempotent APPEND, commit-crash + snapshot-corruption
# recovery, endpoint wire path, cross-replica staleness
JAX_PLATFORMS=cpu python -m pytest tests/test_streaming.py -q -m 'not slow'

echo "== observability: event log + tracing overhead + profiler gate =="
# run the q18 ladder query with telemetry disabled then with the event log
# AND the span plane both on: together they must add <5% wall time, and
# tools/profiler.py must replay the log into a report with a clean schema
# and a non-empty operator breakdown (join build named)
obs_dir=$(mktemp -d)
JAX_PLATFORMS=cpu SRT_OBS_DIR="$obs_dir" python - <<'PYEOF'
import jax; jax.config.update("jax_platforms", "cpu")
import os, statistics, time
import spark_rapids_tpu  # noqa: F401  (enables x64)
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.runtime import eventlog

paths = tpch.generate(0.01, "/tmp/tpch_ci_sf0.01")
REPS = 5

def run(conf):
    spark = TpuSession(conf)
    dfs = tpch.load(spark, paths, files_per_partition=4)
    df = tpch.q18(dfs)
    df.collect()                      # warm (compiles cached after)
    ts = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        df.collect()
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts)

off_s = run({})
# memory profiling rides inside the SAME <5% budget: allocation-site
# accounting is always on, and the fine-grained watermark timeline
# (64k sample interval) is part of the "on" run being timed
# the movement ledger's fine-grained sampling (64k interval) rides inside
# the same budget: capture hooks are always on, emission is part of "on"
on_s = run({"spark.rapids.tpu.eventLog.dir": os.environ["SRT_OBS_DIR"],
            "spark.rapids.tpu.eventLog.healthSample.intervalSeconds": 0.5,
            "spark.rapids.tpu.trace.dir": os.environ["SRT_OBS_DIR"],
            "spark.rapids.tpu.memory.profile.watermarkIntervalBytes": "64k",
            "spark.rapids.tpu.movement.sample.intervalBytes": "64k",
            "spark.rapids.tpu.memory.leak.check": "true"})
eventlog.shutdown()
from spark_rapids_tpu.runtime import tracing
tracing.shutdown_spans()
# the black-box flight recorder is ON by default: its ring must have been
# recording during the timed "on" run (so it rides inside the same <5%
# budget), holding the most recent event-log records for a crash dump
from spark_rapids_tpu.runtime import blackbox
assert blackbox.enabled() and blackbox.ring_len() > 0, (
    blackbox.enabled(), blackbox.ring_len())
overhead = (on_s - off_s) / off_s
print(f"event log + tracing overhead on q18: off={off_s:.4f}s "
      f"on={on_s:.4f}s ({overhead:+.1%})")
# <5% wall-time budget, with a small absolute floor so scheduler noise on a
# loaded CI box cannot flake a sub-25ms delta into a failure
assert on_s <= off_s * 1.05 + 0.02, (on_s, off_s)
PYEOF
obs_log=$(ls "$obs_dir"/events-*.jsonl | head -1)
python tools/profiler.py report "$obs_log" --json > /tmp/obs_report.json
python -c '
import json
r = json.load(open("/tmp/obs_report.json"))
assert r["violations"] == [], r["violations"][:5]
qs = [q for q in r["queries"] if q["operators"]]
assert qs, "no query with a non-empty operator breakdown"
q18 = qs[-1]
names = " ".join(o["op"] for o in q18["operators"])
assert "(build)" in names, names   # the join build is a distinct line item
print("profiler gate ok:", len(qs), "queries,",
      len(q18["operators"]), "operators, self-time coverage",
      q18["coverage"])
'
# memory observability plane from the SAME q18 run: the heap profiler must
# attribute >=90% of the recorded peak to NAMED allocation sites, the
# watermark timeline must be monotone, and a clean run reports zero leaks
python tools/profiler.py memory "$obs_log" > /tmp/obs_memory.txt
grep -q "watermark timeline" /tmp/obs_memory.txt
grep -q "no leaks detected" /tmp/obs_memory.txt
python tools/profiler.py memory "$obs_log" --json > /tmp/obs_memory.json
python -c '
import json
m = json.load(open("/tmp/obs_memory.json"))
assert m["watermarks"], "no watermark samples"
marks = [w["watermark_bytes"] for w in m["watermarks"]]
assert marks == sorted(marks), "watermark ran backwards"
assert not m["leaks"], m["leaks"]
assert m["peak_attribution"] is not None and m["peak_attribution"] >= 0.9, \
    (m["peak_attribution"], m["peak"])
assert m["queries"] and all(q["peak_device_bytes"] > 0 for q in m["queries"])
print("memory profiler gate ok:", len(m["watermarks"]), "samples, peak",
      m["peak"]["device_bytes"], "B, attribution", m["peak_attribution"],
      "to sites", sorted(m["peak"]["sites"]))
'
# the SAME run's span file must export to a Perfetto-loadable trace with a
# non-empty critical path (single-process: operator trace_range spans) AND
# per-process memory counter lanes (ph C) alongside the span lanes
python tools/profiler.py trace "$obs_dir" --out /tmp/obs_trace.json \
  > /tmp/obs_trace.txt
grep -q "bounding edge:" /tmp/obs_trace.txt
python - /tmp/obs_trace.json <<'PYEOF'
import json, sys
t = json.load(open(sys.argv[1]))
cs = [e for e in t["traceEvents"] if e["ph"] == "C" and e["name"] == "memory"]
assert cs, "no memory counter-track samples in the chrome trace"
for e in cs:
    assert set(e["args"]) == {"device_bytes", "host_bytes", "disk_bytes"}, e
print("memory counter lanes ok:", len(cs), "samples")
PYEOF
rm -rf "$obs_dir"

echo "== movement plane: per-link byte ledger gate (3-executor q18) =="
# q18 on a same-host 3-executor MiniCluster: the merged per-process ledgers
# must cover the driver-registered map-output bytes (>=90%), classify every
# transport byte loopback/local (tcp exactly 0 — the misattribution
# regression), leave the retry edge at zero with no faults armed, and keep
# every network edge at exactly zero on the single-process no-shuffle path
mv_dir=$(mktemp -d)
JAX_PLATFORMS=cpu python tools/movement_gate.py \
  --data-dir /tmp/tpch_ci_sf0.01 --eventlog-dir "$mv_dir" --query q18
# the movement read-out merges every per-process event log into one matrix
python tools/profiler.py movement "$mv_dir"/events-*.jsonl \
  > /tmp/mv_readout.txt
grep -q "byte matrix" /tmp/mv_readout.txt
grep -q "heaviest flow:" /tmp/mv_readout.txt
grep -q "loopback-vs-remote:" /tmp/mv_readout.txt
python tools/profiler.py movement "$mv_dir"/events-*.jsonl --json \
  > /tmp/mv_readout.json
python - "$mv_dir" <<'PYEOF'
import glob, json, sys
m = json.load(open("/tmp/mv_readout.json"))
# denominator: the driver-registered per-reduce partition sizes
reg = 0
for path in glob.glob(sys.argv[1] + "/events-*.jsonl"):
    for ln in open(path):
        ln = ln.strip()
        if not ln:
            continue
        rec = json.loads(ln)
        if rec.get("event") == "stage.map.end" \
                and rec.get("partition_sizes"):
            reg += sum(rec["partition_sizes"])
assert reg > 0, "no registered partition sizes in the merged logs"
# the matrix's shuffle row (net -> host, payload units) must agree with
# the registered map-output bytes within 10% (15% headroom upward)
recv = m["matrix"].get("net->host", 0)
assert 0.9 * reg <= recv <= 1.15 * reg, (recv, reg)
by = m["by_link"]
assert by["tcp"] == 0, by
assert by["loopback"] > 0, by
assert m["flows"] and m["queries"], (len(m["flows"]), len(m["queries"]))
amp = [q for q in m["queries"] if q.get("amplification") is not None]
assert amp, "no query carries a movement amplification factor"
print(f"movement read-out gate ok: matrix shuffle row {recv}B vs "
      f"registered {reg}B ({recv / reg:.2f}x), tcp=0, "
      f"loopback={by['loopback']}B, amplification "
      f"{amp[-1]['amplification']}x")
PYEOF
rm -rf "$mv_dir"
# movement-plane unit/integration suite: ledger accounting, link
# classification, retry reclassification under injected faults, the
# 2-executor loopback/local split, and the chaos no-double-count invariant
JAX_PLATFORMS=cpu python -m pytest tests/test_movement.py -q

echo "== statistics plane: plan-history estimate-error gate =="
# q18 twice through a FRESH history dir: run 1 is a cold-start miss whose
# admission estimate comes from the static heuristic; run 2 must hit the
# plan-history store (estimate == run 1's observed device peak), cutting
# the estimate error at least in half WITHOUT changing results (a warm run
# pipelines fewer batches than a compile-stalled cold one, so its peak sits
# below the recorded one — the estimate stays conservative, not exact).
# The footprint floor is
# dropped to 64k because at SF 0.01 the default 16MB floor would dominate
# both runs' estimates and mask the history path entirely.
stats_dir=$(mktemp -d)
JAX_PLATFORMS=cpu SRT_STATS_DIR="$stats_dir" python - <<'PYEOF'
import jax; jax.config.update("jax_platforms", "cpu")
import os
import spark_rapids_tpu  # noqa: F401  (enables x64)
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.runtime import eventlog, metrics

base = os.environ["SRT_STATS_DIR"]
paths = tpch.generate(0.01, "/tmp/tpch_ci_sf0.01")

def run(tag):
    spark = TpuSession({
        "spark.rapids.tpu.eventLog.dir": os.path.join(base, tag),
        "spark.rapids.tpu.stats.history.dir": os.path.join(base, "hist"),
        "spark.rapids.tpu.scheduler.footprint.floorBytes": "64k",
    })
    dfs = tpch.load(spark, paths, files_per_partition=4)
    # hash-repartition lineitem so q18's big aggregate runs behind a real
    # shuffle: per-reduce-partition sizes feed the skew table the read-out
    # gate asserts on (hash on l_orderkey is deliberately uneven)
    dfs["lineitem"] = dfs["lineitem"].repartition(4, "l_orderkey")
    out = tpch.q18(dfs).collect()
    return out, spark.last_query_metrics().stats

out1, st1 = run("run1")
out2, st2 = run("run2")
eventlog.shutdown()
assert st1["history_hit"] is False and st2["history_hit"] is True, (st1, st2)
e1, e2 = st1["estimate_error"], st2["estimate_error"]
# acceptance: run 2's absolute error at most half of run 1's (tiny epsilon
# for peak jitter between a cold and a compile-warm run)
assert e2 <= e1 / 2 + 1e-3, (e1, e2)
assert out1.to_pydict() == out2.to_pydict(), "history changed query results"
res = metrics.resilience_snapshot()
assert not any(res.values()), res
print(f"stats gate ok: estimate error run1={e1:.3f} -> run2={e2:.3f}, "
      f"history_hit={st2['history_hit']}, results identical, "
      f"resilience all-zero")
PYEOF
stats_log=$(ls "$stats_dir"/run2/events-*.jsonl | head -1)
# the plan.stats records must pass the event-log schema (validate_record
# runs inside the profiler's load), and the stats read-out must print the
# per-node ledger and name q18's skewed reduce partition
python tools/profiler.py stats "$stats_log" > /tmp/stats_readout.txt
grep -q "node ledger" /tmp/stats_readout.txt
grep -q "at partition" /tmp/stats_readout.txt
python tools/profiler.py stats "$stats_log" --json > /tmp/stats_readout.json
python -c '
import json
d = json.load(open("/tmp/stats_readout.json"))
assert d["violations"] == [], d["violations"][:5]
qs = [q for q in d["queries"] if q["stats"]]
assert qs and qs[-1]["stats"]["history_hit"] is True, "no history hit"
assert qs[-1]["shuffles"], "no shuffle skew rows for q18"
print("stats read-out gate ok:", len(qs), "queries with plan.stats,",
      len(qs[-1]["shuffles"]), "shuffle skew rows")
'
rm -rf "$stats_dir"

echo "== api coverage gate (0 missing vs reference GpuOverrides) =="
python tools/api_validation.py 0 0

echo "== config docs in sync =="
python -m spark_rapids_tpu.config
git diff --exit-code docs/configs.md || {
  echo "docs/configs.md out of date: run python -m spark_rapids_tpu.config"; exit 1; }

echo "== installable package (dist-jar analog) =="
# import + run a query from the INSTALLED package, outside the repo dir
instdir=$(mktemp -d)
# --no-build-isolation: the CI box has no egress; setuptools is preinstalled
pip install --quiet --no-build-isolation --target "$instdir" --no-deps .
(cd /tmp && PYTHONPATH="$instdir" JAX_PLATFORMS=cpu python - <<'PYEOF'
import jax; jax.config.update("jax_platforms", "cpu")
import spark_rapids_tpu, pyarrow as pa
assert "/repo/" not in spark_rapids_tpu.__file__, spark_rapids_tpu.__file__
from spark_rapids_tpu.session import TpuSession
spark = TpuSession()
spark.create_or_replace_temp_view(
    "t", spark.create_dataframe(pa.table({"k": [1, 2, 2], "v": [1.0, 2.0, 3.0]})))
out = spark.sql("select k, sum(v) s from t group by k order by k").collect()
assert out.to_pylist() == [{"k": 1, "s": 1.0}, {"k": 2, "s": 5.0}], out
print("installed-package query ok")
PYEOF
)
rm -rf "$instdir"

echo "CI OK"
